"""rounds_per_step: R rounds scanned in one compiled program must reproduce
the R-single-round trajectory exactly."""

import numpy as np
import jax

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.orchestration.loop import run_experiment
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.utils.trees import clone
from fedtpu.parallel.round import build_round_fn, init_federated_state


def test_scanned_rounds_match_single_round_trajectory():
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig())
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}

    state_a = init_federated_state(jax.random.key(3), mesh, 8, init_fn, tx)
    state_b = clone(state_a)

    single = build_round_fn(mesh, apply_fn, tx, 2, rounds_per_step=1)
    scanned = build_round_fn(mesh, apply_fn, tx, 2, rounds_per_step=4)

    accs = []
    for _ in range(4):
        state_a, m = single(state_a, batch)
        accs.append(float(m["client_mean"]["accuracy"]))

    state_b, ms = scanned(state_b, batch)
    np.testing.assert_allclose(
        np.asarray(ms["client_mean"]["accuracy"]), accs, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(state_b["params"]["layers"][0]["w"]),
        np.asarray(state_a["params"]["layers"][0]["w"]), atol=1e-5)
    assert int(state_b["round"]) == int(state_a["round"]) == 4
    # Stacked metric shapes: (R,) scalars, (R, C) per-client.
    assert ms["loss"].shape == (4, 8)
    assert ms["per_client"]["f1"].shape == (4, 8)
    assert ms["pooled"]["accuracy"].shape == (4,)


def test_loop_with_chunking_matches_unchunked_history():
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=7),
    )
    res1 = run_experiment(base, verbose=False)
    res3 = run_experiment(
        base.replace(run=RunConfig(rounds_per_step=3)), verbose=False)
    assert res3.rounds_run == 7  # chunks 3+3+1, remainder handled
    np.testing.assert_allclose(res3.global_metrics["accuracy"],
                               res1.global_metrics["accuracy"], atol=1e-6)
    np.testing.assert_allclose(res3.pooled_metrics["f1"],
                               res1.pooled_metrics["f1"], atol=1e-6)


def test_chunked_early_stop_truncates_history():
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=50, termination_patience=3, tolerance=1.0),
        run=RunConfig(rounds_per_step=8),
    )
    res = run_experiment(cfg, verbose=False)
    assert res.stopped_early
    # Same stop round as the unchunked case: prev set at r1, countdown r2-r4.
    assert res.rounds_run == 4
    assert len(res.global_metrics["accuracy"]) == 4
