"""Test harness: run every test on a virtual 8-device CPU mesh.

This is the standard JAX fake-backend trick (SURVEY.md §4): force the host
platform to expose 8 devices so multi-client mesh code runs (and collectives
execute) without TPU hardware. Must be set before jax initializes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Boxes with a TPU PJRT plugin but no TPU (or no metadata service) spend
# minutes in libtpu's 30-try GCP metadata fetch before giving up; skip the
# query so backend discovery fails fast. Inherited by subprocess tests
# (test_graft_entry strips only XLA_FLAGS/JAX_PLATFORMS), whose un-pinned
# `jax.devices()` preambles otherwise stall past the suite budget.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some environments pre-register an accelerator PJRT plugin at interpreter
# start and force jax_platforms to it; re-force CPU before any backend is
# initialized so the 8 virtual devices take effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")

import pytest  # noqa: E402

# ---------------------------------------------------------------- quick tier
# `pytest -m quick` — the CI-fast tier (VERDICT r1 item 7). Round-3
# re-tune: the r2 selection had crept to 2:42 on this box and was
# re-profiled with --durations and trimmed twice; measured 110 s on the
# quiet 1-core verification box, up to ~2:15 when the box is contended
# (the spread is host load, not the selection — the same set varied
# 110-134 s across one afternoon). At least one test from EVERY
# in-process test module (so a quick run still touches every fedtpu
# subsystem; the two subprocess modules are excluded by name below).
# The full suite (259 tests, ~25 min on this box) remains the merge
# gate; the quick tier is the inner-loop iteration gate. Names, not
# patterns, so a typo'd or gone-stale entry fails loudly via the
# consistency guards at the bottom of pytest_collection_modifyitems
# below.
QUICK_TESTS = {
    # round-3 modules
    "test_advisor_r3.py::test_peak_flops_negative_slope_warns",
    "test_dp_accountant.py::test_abadi_et_al_canonical_value",
    "test_dp_accountant.py::test_full_participation_matches_closed_form",
    "test_dp_accountant.py::test_monotonicity",
    "test_dp_accountant.py::test_edge_cases",
    "test_sweep.py::test_plateau_stop_freezes_exactly_at_the_plateau_point",
    "test_stop_lag.py::test_fedtpu_stops_at_the_reference_trained_round_count",
    "test_checkpoint.py::test_latest_step_skips_half_written_rounds",
    "test_checkpoint.py::test_retention_keeps_k_newest_plus_protected",
    "test_combo_matrix.py::"
    "test_combo_round_executes_or_raises_cleanly[plain-none]",
    "test_combo_matrix.py::"
    "test_combo_round_executes_or_raises_cleanly[median-sample]",
    "test_convnet.py::test_convnet_accepts_nhwc_and_flat_inputs",
    "test_local_steps.py::test_local_steps_equals_rounds_for_single_client",
    # aux subsystems (cifar fallback, multihost in-process; the divergence
    # halt is quick-covered by test_pipelined_stop's variant)
    "test_aux_subsystems.py::test_cifar10_synthetic_fallback_shapes",
    "test_aux_subsystems.py::test_synthetic_cifar_deterministic",
    "test_aux_subsystems.py::test_multihost_single_process_paths",
    "test_aux_subsystems.py::test_local_client_slice_multiprocess_simulated",
    "test_aux_subsystems.py::test_looks_multihost_env_detection",
    "test_aux_subsystems.py::test_lazy_top_level_api_resolves",
    "test_chunk_regressions.py::test_no_checkpoint_after_midchunk_early_stop",
    "test_cli.py::test_presets_listing",
    "test_cli.py::test_sweep_bad_table_path_fails_fast",
    "test_cli.py::test_run_new_aggregation_flags_reach_config",
    "test_compilation.py::test_fingerprint_moves_with_the_program",
    "test_compilation.py::test_executor_dedupes_blocks_and_reraises",
    "test_compilation.py::"
    "test_fingerprint_is_stable_across_concrete_and_abstract_args",
    "test_compress.py::test_quantize_roundtrip_error_bound",
    "test_compress.py::test_quantize_zero_delta_is_exact",
    "test_compress.py::test_quantize_preserves_extremes",
    "test_compress.py::test_dequantize_broadcasts_gathered_scales",
    "test_compress.py::test_compress_rejects_delta_path_and_ring",
    "test_compress.py::test_compress_rejects_state_without_shared_start",
    "test_data.py::test_synthetic_dataset_shapes",
    "test_data.py::test_income_csv_pipeline_matches_reference_semantics",
    "test_data.py::test_split_bit_parity_with_sklearn",
    "test_data.py::test_contiguous_shards_partition_with_remainder",
    "test_data.py::test_shared_seed_shuffle_is_a_partition",
    "test_data.py::test_unseeded_bug_parity_shards_overlap",
    "test_data.py::test_dirichlet_shards_partition_and_skew",
    "test_data.py::test_pack_clients_masks_and_counts",
    "test_fedavg.py::test_weighted_average_matches_numpy_oracle",
    "test_fedavg.py::test_uniform_average_matches_plain_mean",
    "test_fedavg.py::test_unequal_shards_weight_by_true_counts",
    "test_fedavg.py::test_optimizer_state_is_not_averaged",
    "test_graft_entry.py::"
    "test_dryrun_after_backend_init_without_flag_raises_cleanly",
    "test_loop.py::test_run_experiment_history_shapes",
    "test_metrics.py::test_metrics_match_sklearn[2-0]",
    "test_metrics.py::test_zero_division_semantics",
    "test_metrics.py::test_mask_excludes_padding",
    "test_metrics.py::test_summed_confusions_equal_concatenated_predictions",
    "test_multiround.py::test_chunked_early_stop_truncates_history",
    "test_native_loader.py::test_income_csv_native_matches_pandas",
    "test_native_loader.py::test_quoting_crlf_and_missing_trailing_newline",
    "test_native_loader.py::test_ragged_row_is_an_error",
    "test_optim.py::test_adam_steplr_matches_torch_trajectory",
    "test_optim.py::test_schedule_staircase_boundaries",
    "test_optim.py::test_onehot_ce_equals_gather_ce",
    "test_pallas.py::test_weighted_average_kernel_matches_numpy",
    "test_parity.py::test_limitation_demonstrated",
    "test_participation.py::test_sampled_average_over_participants_only",
    "test_program_audit.py::test_extract_schedule_counts_psum_bytes",
    "test_program_audit.py::test_branch_divergent_schedule_flags_aud001",
    "test_program_audit.py::test_donation_proof_flags_unaliased_aud002",
    "test_audit_gate.py::test_goldens_are_clean_contracts",
    "test_personalize.py::test_personalize_rejects_zero_steps",
    "test_pipelined_stop.py::test_pipelined_divergence_still_halts",
    "test_privacy_ledger.py::test_checkpoint_meta_roundtrips_exactly",
    "test_privacy_ledger.py::test_zero_order_overlap_projects_finite_not_inf",
    "test_privacy_ledger.py::test_noise_off_resume_never_zeroes"
    "_restored_spend",
    "test_privacy_ledger.py::test_guarantee_void_when_training_unnoised"
    "_after_noised",
    "test_review_fixes.py::test_numeric_labels_reencoded_to_contiguous_indices",
    "test_review_fixes.py::test_empty_shards_excluded_from_client_mean",
    "test_ring.py::test_ring_matches_global_sum[shape0-ring_all_reduce_sum]",
    "test_ring.py::test_ring_matches_global_sum"
    "[shape0-ring_all_reduce_sum_rsag]",
    "test_ring.py::test_pallas_rdma_ring_matches_global_sum[shape0]",
    "test_robust.py::test_median_matches_numpy_oracle",
    "test_robust.py::test_trimmed_mean_matches_numpy_oracle",
    "test_robust.py::test_krum_matches_numpy_oracle",
    "test_robust.py::test_geometric_median_matches_numpy_weiszfeld",
    "test_robust.py::test_robust_rejects_bad_combos",
    "test_robust.py::test_weiszfeld_iteration_budget_converges",
    "test_robust_defense.py::"
    "test_poisoned_user_ids_is_deterministic_and_validated",
    "test_robust_defense.py::test_trace_reader_rejects_future_schema",
    "test_robust_defense.py::"
    "test_defense_sim_compare_reports_first_divergence",
    "test_robust_defense.py::test_cohort_sampler_refuses_quarantined_ids",
    "test_round_smoke.py::test_empty_hidden_sizes_is_logistic_regression",
    "test_server_opt.py::test_update_rules_match_numpy_oracle",
    "test_server_opt.py::test_clip_by_global_norm_is_per_client_joint",
    "test_server_opt.py::test_unknown_server_opt_rejected",
    "test_server_opt.py::test_missing_server_state_is_a_clear_error",
    "test_server_opt.py::test_stale_server_state_is_a_clear_error",
    "test_server_opt.py::test_dp_noise_requires_clip",
    "test_timing.py::test_force_fetch_returns_scalar_from_tree",
    "test_timing.py::test_force_fetch_depends_on_computation",
    "test_timing.py::test_force_fetch_refuses_host_only_trees",
    "test_timing.py::test_flops_floor_passes_above_and_raises_below",
    "test_timing.py::test_measured_peak_flops_is_positive_and_sane",
    "test_timing.py::test_timer_laps",
    "test_tp.py::test_mesh_2d_shape",
    "test_tp.py::test_unsupported_combos_raise",
    "test_tp.py::test_per_device_state_bytes_scale_down_with_tp",
    # round-4 modules
    # telemetry subsystem (tracer/report/satellites; backend-free picks)
    "test_telemetry.py::test_event_schema_roundtrip",
    # causal fleet tracing (docs/observability.md): trace_id/flight
    # recorder/merged identity keying are backend-free milliseconds;
    # the sim golden gate stays full-tier (it compiles the engines).
    "test_timeline.py::test_trace_id_deterministic_across_retry",
    "test_timeline.py::test_flight_recorder_ring_bounds",
    "test_timeline.py::test_merged_report_keys_colliding_run_ids",
    "test_timeline.py::test_timeline_merges_and_orders_chains",
    "test_telemetry.py::test_bench_json_is_last_stdout_line",
    "test_telemetry.py::test_drop_nonwinning_weights_frees_losers",
    "test_telemetry.py::test_no_bare_prints_outside_allowlist",
    "test_scaffold.py::test_server_cv_is_mean_of_client_cv",
    "test_scaffold.py::test_incompatible_combos_raise",
    "test_adaptive_clip.py::test_effective_delta_noise_multiplier_identity",
    "test_adaptive_clip.py::test_one_round_clip_update_matches_oracle",
    "test_async.py::test_guards",
    "test_async.py::test_staleness_bookkeeping_under_sampling",
    # round-5 modules
    # static-analysis subsystem (rule engine is pure AST — both picks are
    # backend-free and fast)
    "test_analysis.py::test_rule_fixtures_catch_seeded_violations",
    "test_analysis.py::test_text_reporter_golden",
    "test_lint_gate.py::test_repo_lint_gate_is_clean",
    # concurrency/determinism auditor (PR 17): the lockdep drills and the
    # fixed-finding regressions are backend-free and run in milliseconds;
    # the subprocess exit-code fold stays full-tier.
    "test_lockdep.py::test_abba_ordering_is_detected_as_a_cycle",
    "test_lockdep.py::test_drills_match_committed_golden_bitwise",
    "test_concurrency_fixes.py::"
    "test_send_msg_bytes_are_canonical_across_insertion_order",
    "test_concurrency_fixes.py::"
    "test_reshard_handler_fires_while_main_thread_polls",
    # test_multihost_e2e spawns 2 OS processes (~70 s for the round-kernel
    # worker since the int8/Byzantine sections joined) and stays full-tier
    # only; fedtpu/parallel/multihost.py is covered above in-process.
    # test_chaos_resume SIGKILLs subprocess CLI runs (~60 s) and stays
    # full-tier only; the resume machinery is covered by test_checkpoint.
    # round-6 modules
    # resilience subsystem (fault plans, rollback, supervisor contract —
    # both picks are backend-free and run in milliseconds)
    "test_resilience.py::test_plan_spec_forms_are_identical",
    "test_resilience.py::test_chunk_limit_isolates_fault_rounds",
    # round-7 modules
    # serving subsystem (admission + trace schema — both backend-free,
    # milliseconds; the engine/socket tests stay full-tier)
    "test_serving.py::"
    "test_admission_check_order_is_rate_backpressure_staleness",
    "test_serving.py::test_trace_roundtrip_and_header",
    # round-8 modules
    # cohort subsystem (sampler + store are backend-free numpy,
    # milliseconds; the parity/resume/RSS tests stay full-tier)
    "test_cohort.py::test_sampler_uniform_full_population_is_identity",
    "test_cohort.py::test_store_roundtrip_memory_and_mmap",
    "test_cohort.py::test_cohort_config_guards",
    # test_chaos_supervised runs supervised subprocess CLI children
    # (kill + restart, ~90 s) and stays full-tier only; the in-process
    # resilience semantics are covered by test_resilience above.
    # round-9 modules
    # elastic reshard (planner + controller are backend-free numpy/
    # filesystem, milliseconds; the integrated shrink/grow loop tests
    # stay full-tier)
    "test_reshard.py::test_row_maps",
    "test_reshard.py::test_spool_roundtrip_and_generation_fence",
    "test_reshard.py::test_signal_agreement_converges",
    # round-10 modules
    # autoscale control plane (policy/bus/simulator are backend-free,
    # seconds; the engine integration, report merge, and chaos drill
    # stay full-tier)
    "test_autoscale.py::test_simulate_decision_sequence_is_bitwise"
    "_deterministic",
    "test_autoscale.py::test_threshold_policy_requires_consecutive"
    "_hot_ticks",
    "test_autoscale.py::test_signal_bus_folds_stats_and_prefers"
    "_exported_burn",
    # round-11 modules
    # gateway fleet (routing/redirect/session-dedup are backend-free or
    # tiny-engine, milliseconds-to-seconds; the socket fleet and chaos
    # rows stay full-tier)
    "test_gateway.py::test_owner_of_and_redirect_msg",
    "test_gateway.py::test_client_partition_matches_gateway_owner",
    "test_gateway.py::test_retried_frame_incorporated_exactly_once",
    # round-12 modules
    # wire faults (plan materialization, the scenario registry pin, and
    # the streaming line cap are backend-free, milliseconds; the proxy
    # end-to-end, the net-sim golden, and the live chaos rows stay
    # full-tier)
    "test_netfaults.py::test_plan_spec_forms_are_identical",
    "test_netfaults.py::test_plan_validation_rejects_bad_entries",
    "test_netfaults.py::test_scenario_registry_is_single_source_of_truth",
    "test_netfaults.py::test_line_cap_streams_bounded_and_connection"
    "_survives",
    # round-13 modules
    # MPMD round pipelining (PR 18): the width-1 two-program DAG parity
    # run is the fastest compile in the module (~seconds); the golden
    # contract check is pure JSON, milliseconds. The chain/SIGTERM/
    # trace-chain parity runs stay full-tier.
    "test_mpmd.py::test_mpmd_width1_matches_monolithic_bitwise",
    "test_mpmd_audit_gate.py::test_mpmd_goldens_are_clean_contracts",
    # round-14 modules
    # compositional chaos fuzzing (PR 19): campaign digests, the oracle
    # library, and the chaos-bar equivalence pins are backend-free,
    # milliseconds; the multi-campaign sweep and ddmin-from-noise runs
    # stay full-tier. The corpus bitwise-replay gate itself runs quick
    # via test_corpus_campaigns... in the tier-1 flow (seconds).
    "test_fuzz.py::test_campaign_digest_roundtrip",
    "test_fuzz.py::test_sampler_is_deterministic_and_covers"
    "_the_fault_space",
    "test_fuzz.py::test_judge_gateway_kill_matches_legacy"
    "_mp_gateway_kill_bar",
    "test_fuzz.py::test_judge_net_row_matches_legacy_mp_torn_frame_bar",
    "test_fuzz.py::test_restart_backoff_is_a_pure_function_of_exit"
    "_and_streak",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: CI-fast tier (<2 min) touching every test module; "
        "run with `pytest -m quick`")
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` flow (ROADMAP.md); "
        "full-tier only")


def pytest_collection_modifyitems(config, items):
    matched = set()
    modules_all = set()
    modules_quick = set()
    for item in items:
        rel = item.nodeid.split("tests/")[-1]
        modules_all.add(rel.split("::")[0])
        if rel in QUICK_TESTS:
            item.add_marker(pytest.mark.quick)
            matched.add(rel)
            modules_quick.add(rel.split("::")[0])
    # Consistency guards — scoped to what was actually collected, so
    # single-file and --ignore runs never false-positive:
    quick_modules_expected = {t.split("::")[0] for t in QUICK_TESTS}
    if quick_modules_expected <= modules_all:
        # Every module QUICK_TESTS references was collected, so every entry
        # must have matched a real test — anything left is stale/renamed.
        stale = QUICK_TESTS - matched
        if stale:
            raise pytest.UsageError(
                f"conftest QUICK_TESTS entries match nothing (renamed or "
                f"removed tests?): {sorted(stale)}")
    uncovered = (modules_all - modules_quick
                 - {"test_multihost_e2e.py", "test_chaos_resume.py",
                    "test_chaos_supervised.py", "test_gang_resilience.py"}
                 if quick_modules_expected <= modules_all else set())
    if uncovered:
        raise pytest.UsageError(
            f"test modules with no quick-tier test: {sorted(uncovered)}")


# ------------------------------------------------------- native-cache hygiene
# The full suite compiles hundreds of XLA programs across 36 modules; the
# executables (and their buffers) accumulate memory MAPPINGS for the whole
# pytest process lifetime. Around ~280 tests in, the map count approaches
# the kernel's default vm.max_map_count (65530) and the next native mmap
# fails => C++ abort => "Fatal Python error: Aborted" in whichever test
# happens to run there (observed twice, deterministically, in
# test_robust.py — a test that passes alone in seconds). Dropping JAX's
# compilation caches at module boundaries releases the executables;
# cross-module cache hits are rare (each module compiles its own shapes),
# so the wall-clock cost is negligible next to the crash it prevents.
@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    import gc

    import jax

    jax.clear_caches()
    gc.collect()
