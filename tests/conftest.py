"""Test harness: run every test on a virtual 8-device CPU mesh.

This is the standard JAX fake-backend trick (SURVEY.md §4): force the host
platform to expose 8 devices so multi-client mesh code runs (and collectives
execute) without TPU hardware. Must be set before jax initializes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some environments pre-register an accelerator PJRT plugin at interpreter
# start and force jax_platforms to it; re-force CPU before any backend is
# initialized so the 8 virtual devices take effect.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")
