"""Worker for the multi-process (DCN-path) integration test.

Launched by tests/test_multihost_e2e.py as 2 OS processes, each exposing 4
virtual CPU devices; jax.distributed wires them into ONE runtime with 8
global devices, and the standard fedtpu round program runs over the global
('clients',) mesh — collectives cross the process boundary over TCP/gloo,
the CPU stand-in for DCN. This is the executable version of the
fedtpu.parallel.multihost contract (the reference's `mpirun --hostfile`
analogue, SURVEY.md §2c).

Writes, per process: the post-round global model (every client slot holds
it) and the client-mean accuracy, for the parent test to cross-check.
"""

import os
import sys

# Shared experiment constants — imported by tests/test_multihost_e2e.py for
# its single-process cross-check, so the two programs cannot drift.
ROWS, FEATURES, CLASSES = 200, 6, 2
NUM_CLIENTS = 8
HIDDEN = (8,)
SEED = 1
ROUNDS_PER_STEP = 2
OUTER_STEPS = 2


def main():
    pid, nprocs, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from fedtpu.parallel import multihost

    # Before ANY other jax usage (the jax.distributed contract).
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == 4 * nprocs

    import numpy as np
    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel.mesh import make_mesh
    from fedtpu.parallel.round import build_round_fn, init_federated_state

    # Deterministic synthetic data — identical on every process.
    x, y = synthetic_income_like(ROWS, FEATURES, CLASSES)
    packed = pack_clients(x, y, ShardConfig(num_clients=NUM_CLIENTS,
                                            shuffle=False))

    mesh = make_mesh(num_clients=NUM_CLIENTS)    # global 8-device mesh
    batch = multihost.distribute_client_batch(packed, mesh)

    init_fn, apply_fn = build_model(ModelConfig(input_dim=FEATURES,
                                                hidden_sizes=HIDDEN))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(SEED), mesh, NUM_CLIENTS,
                                 init_fn, tx, same_init=True)
    step = build_round_fn(mesh, apply_fn, tx, CLASSES,
                          rounds_per_step=ROUNDS_PER_STEP)

    for _ in range(OUTER_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(state["params"])

    # Every client slot holds the averaged global model; read this
    # process's first addressable slot.
    leaf = jax.tree.leaves(state["params"])[0]
    local0 = np.asarray(leaf.addressable_shards[0].data)[0]
    acc = float(np.asarray(metrics["client_mean"]["accuracy"])[-1])

    np.save(os.path.join(outdir, f"params_{pid}.npy"), local0)
    with open(os.path.join(outdir, f"acc_{pid}.txt"), "w") as f:
        f.write(repr(acc))
    print(f"worker {pid}: ok acc={acc:.4f}", flush=True)


if __name__ == "__main__":
    main()
