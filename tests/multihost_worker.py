"""Worker for the multi-process (DCN-path) integration test.

Launched by tests/test_multihost_e2e.py as 2 OS processes, each exposing 4
virtual CPU devices; jax.distributed wires them into ONE runtime with 8
global devices, and the standard fedtpu round program runs over the global
('clients',) mesh — collectives cross the process boundary over TCP/gloo,
the CPU stand-in for DCN. This is the executable version of the
fedtpu.parallel.multihost contract (the reference's `mpirun --hostfile`
analogue, SURVEY.md §2c).

Writes, per process: the post-round global model (every client slot holds
it) and the client-mean accuracy, for the parent test to cross-check.
"""

import os
import sys

# Shared experiment constants — imported by tests/test_multihost_e2e.py for
# its single-process cross-check, so the two programs cannot drift.
ROWS, FEATURES, CLASSES = 200, 6, 2
NUM_CLIENTS = 8
HIDDEN = (8,)
SEED = 1
ROUNDS_PER_STEP = 2
OUTER_STEPS = 2


def main():
    pid, nprocs, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], sys.argv[4])
    # Per-process virtual device count: the launcher scales processes and
    # devices inversely (2 procs x 4 devices, 4 procs x 2 devices) so the
    # global mesh is always the same 8 devices.
    local = int(os.environ.get("FEDTPU_TEST_LOCAL_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={local}"
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from fedtpu.parallel import multihost

    # Before ANY other jax usage (the jax.distributed contract).
    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert len(jax.devices()) == local * nprocs

    import numpy as np
    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel.mesh import make_mesh
    from fedtpu.parallel.round import build_round_fn, init_federated_state

    # Deterministic synthetic data — identical on every process.
    x, y = synthetic_income_like(ROWS, FEATURES, CLASSES)
    packed = pack_clients(x, y, ShardConfig(num_clients=NUM_CLIENTS,
                                            shuffle=False))

    mesh = make_mesh(num_clients=NUM_CLIENTS)    # global 8-device mesh
    batch = multihost.distribute_client_batch(packed, mesh)

    init_fn, apply_fn = build_model(ModelConfig(input_dim=FEATURES,
                                                hidden_sizes=HIDDEN))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(SEED), mesh, NUM_CLIENTS,
                                 init_fn, tx, same_init=True)
    step = build_round_fn(mesh, apply_fn, tx, CLASSES,
                          rounds_per_step=ROUNDS_PER_STEP)

    for _ in range(OUTER_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(state["params"])

    # Every client slot holds the averaged global model; read this
    # process's first addressable slot.
    leaf = jax.tree.leaves(state["params"])[0]
    local0 = np.asarray(leaf.addressable_shards[0].data)[0]
    acc = float(np.asarray(metrics["client_mean"]["accuracy"])[-1])

    np.save(os.path.join(outdir, f"params_{pid}.npy"), local0)
    with open(os.path.join(outdir, f"acc_{pid}.txt"), "w") as f:
        f.write(repr(acc))
    print(f"worker {pid}: ok acc={acc:.4f}", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol

    # --- Explicit ring (ppermute) aggregation ACROSS the process boundary.
    # psum lets XLA choose the collective; the ring path spells out the
    # rotate-accumulate schedule (fedtpu/parallel/ring.py) — here its
    # ppermute hops genuinely cross processes over TCP/gloo. One round from
    # a fresh same-init state must match the psum path bit-for-bit up to
    # reassociation.
    from fedtpu.parallel.mesh import replicated_sharding
    from fedtpu.utils.trees import identity

    def fetch_global(tree, m):
        """Full global host value of a sharded pytree: replicate in-graph
        (collective — every process executes it), then fetch locally.
        Module-level `identity` so repeated calls hit the jit cache."""
        rep = jax.jit(identity, out_shardings=replicated_sharding(m))
        return jax.tree.map(np.asarray, rep(tree))

    ring_state = init_federated_state(jax.random.key(SEED), mesh,
                                      NUM_CLIENTS, init_fn, tx,
                                      same_init=True)
    psum_state = init_federated_state(jax.random.key(SEED), mesh,
                                      NUM_CLIENTS, init_fn, tx,
                                      same_init=True)
    ring_step = build_round_fn(mesh, apply_fn, tx, CLASSES,
                               aggregation="ring")
    psum_step = build_round_fn(mesh, apply_fn, tx, CLASSES)
    ring_state, _ = ring_step(ring_state, batch)
    psum_state, _ = psum_step(psum_state, batch)
    ring_g = fetch_global(ring_state["params"], mesh)
    psum_g = fetch_global(psum_state["params"], mesh)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                 ring_g, psum_g)
    print(f"worker {pid}: ring == psum across processes ok", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol

    # --- True tp-over-DCN: a ('clients','model') mesh whose MODEL-axis
    # pairs each span BOTH processes (devices [[0,4],[1,5],[2,6],[3,7]]),
    # so the Megatron col/row collectives themselves cross the process
    # boundary — unlike make_mesh_2d's default layout, where tp pairs are
    # intra-process. One 2-D round must match the 1-D engine's round.
    from jax.sharding import Mesh
    from fedtpu.parallel import tp
    from fedtpu.parallel.mesh import CLIENTS_AXIS

    devs = np.asarray(jax.devices()).reshape(2, 4).T   # (4, 2): tp crosses
    mesh2 = Mesh(devs, (CLIENTS_AXIS, tp.MODEL_AXIS))
    shard2 = tp.batch_sharding_2d(mesh2)
    # Same host-global data on every process + cross-process sharding —
    # the pattern build_experiment relies on.
    batch2 = {k: jax.device_put(v, shard2)
              for k, v in {"x": packed.x, "y": packed.y,
                           "mask": packed.mask}.items()}
    state2 = tp.init_federated_state_2d(jax.random.key(SEED), mesh2,
                                        NUM_CLIENTS, init_fn, tx,
                                        same_init=True)
    step2 = tp.build_round_fn_2d(mesh2, apply_fn, tx, CLASSES)
    state2, m2 = step2(state2, batch2)
    tp_g = fetch_global(state2["params"], mesh2)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4),
                 tp_g, psum_g)
    acc2 = float(np.asarray(m2["client_mean"]["accuracy"]))
    with open(os.path.join(outdir, f"tp_acc_{pid}.txt"), "w") as f:
        f.write(repr(acc2))
    print(f"worker {pid}: tp-over-DCN round ok acc={acc2:.4f}", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol

    # --- int8-quantized exchange across the process boundary: the
    # all_gather of int8 payloads + per-client scales crosses TCP (the
    # wire-size win this mode exists for — D/8 of the f32 psum traffic).
    # One round must stay within quantization error of exact averaging.
    q_state = init_federated_state(jax.random.key(SEED), mesh, NUM_CLIENTS,
                                   init_fn, tx, same_init=True,
                                   shared_start=True)
    q_step = build_round_fn(mesh, apply_fn, tx, CLASSES, compress="int8")
    q_state, qm = q_step(q_state, batch)
    q_g = fetch_global(q_state["params"], mesh)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4),
                 q_g, psum_g)
    assert np.isfinite(float(np.asarray(qm["client_mean"]["accuracy"])))
    print(f"worker {pid}: int8 exchange across processes ok", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol

    # --- Byzantine-robust median with the attack crossing the boundary:
    # clients 0-1 (process 0's devices) submit 10x sign-flipped updates;
    # the order statistics run on all_gather'd values spanning both
    # processes. The median must hold the global near the honest step
    # while the plain mean is dragged past it.
    def one_round_move(**round_kw):
        s = init_federated_state(jax.random.key(SEED), mesh, NUM_CLIENTS,
                                 init_fn, tx, same_init=True)
        start = jax.tree.leaves(fetch_global(s["params"], mesh))[0][0]
        r_step = build_round_fn(mesh, apply_fn, tx, CLASSES,
                                weighting="uniform", **round_kw)
        s, _ = r_step(s, batch)
        end = jax.tree.leaves(fetch_global(s["params"], mesh))[0][0]
        return float(np.abs(end - start).max())

    honest = one_round_move()
    attacked_mean = one_round_move(byzantine_clients=2)
    defended = one_round_move(byzantine_clients=2,
                              robust_aggregation="median")
    assert attacked_mean > 1.5 * honest, (honest, attacked_mean)
    assert defended <= 1.5 * honest, (honest, defended)
    print(f"worker {pid}: median holds under cross-process Byzantine "  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol
          f"injection ok (honest {honest:.2e}, mean {attacked_mean:.2e}, "
          f"median {defended:.2e})", flush=True)


if __name__ == "__main__":
    main()
