"""Feature-combination matrix: one tiny round for every VALID pairing of
the aggregation-path knobs, asserting the round executes, stays finite,
and keeps every client slot synchronized on the new global.

The individual features are each pinned by their own module; what this
module guards is the CROSS-feature surface (e.g. local_steps x compress,
participation x server_opt, robust x rounds_per_step) where an
interaction bug would hide from per-feature tests. Invalid combinations
are asserted to raise — the documented constraint matrix of
fedtpu/parallel/round.py, exercised as a matrix rather than ad hoc.
"""

import itertools

import jax
import numpy as np
import pytest

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import make_server_optimizer
from fedtpu.parallel import client_sharding, make_mesh
from fedtpu.parallel.round import build_round_fn, init_federated_state

NUM_CLIENTS = 8


def _fixtures():
    x, y = synthetic_income_like(64, 4, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=NUM_CLIENTS,
                                            shuffle=False))
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=4,
                                                hidden_sizes=(4,)))
    tx = build_optimizer(OptimConfig())
    return mesh, batch, init_fn, apply_fn, tx


# One axis per aggregation-path knob; entries are build_round_fn kwargs.
BASES = {
    "plain": {},
    "ring": dict(aggregation="ring"),
    "fedadam": dict(server_opt="fedadam"),
    "dp": dict(dp_clip_norm=1.0, dp_noise_multiplier=0.1,
               weighting="uniform"),
    "int8": dict(compress="int8"),
    "median": dict(robust_aggregation="median", weighting="uniform"),
    # round-4 knobs
    "scaffold": dict(scaffold=True, weighting="uniform",
                     server_opt="fedavgm"),
    "adaptive": dict(dp_clip_norm=1.0, dp_noise_multiplier=0.1,
                     dp_adaptive_clip=True, dp_count_noise_multiplier=0.5,
                     weighting="uniform"),
}
MODIFIERS = {
    "none": {},
    "local5": dict(local_steps=5),
    "prox": dict(local_steps=3, prox_mu=0.1),
    "sample": dict(participation_rate=0.5),
    "scan3": dict(rounds_per_step=3),
    "byz": dict(byzantine_clients=2, weighting="uniform"),
}

# Combinations build_round_fn documents as unsupported (it raises);
# everything else must run. Kept as data so a constraint change shows up
# as a diff here. Notable VALID pairings the matrix proves: DP+sampling
# (fixed q*C denominator), server-opt+sampling, int8+Byzantine,
# DP+Byzantine (clip bounds the poison), robust+Byzantine (the
# attack/defense pairing).
EXPECT_RAISE = {
    # ("median", "sample") raised until the robust validator learned
    # that coordinate-wise rules compose with sampling (docs/robustness.md);
    # the combo now executes below.
    ("scaffold", "byz"),       # variate/poison attack model incoherent
}


def _merged(base: str, mod: str):
    # Every axis entry that sets `weighting` sets "uniform", so the plain
    # merge is already consistent.
    return {**BASES[base], **MODIFIERS[mod]}


@pytest.mark.parametrize("base,mod",
                         list(itertools.product(BASES, MODIFIERS)))
def test_combo_round_executes_or_raises_cleanly(base, mod):
    kw = _merged(base, mod)

    server = None
    if "server_opt" in kw:
        server = make_server_optimizer(kw.pop("server_opt"),
                                       learning_rate=0.02)

    if (base, mod) in EXPECT_RAISE:
        mesh, _, init_fn, apply_fn, tx = _fixtures()
        with pytest.raises(ValueError):
            build_round_fn(mesh, apply_fn, tx, 2, server_opt=server, **kw)
        return

    mesh, batch, init_fn, apply_fn, tx = _fixtures()
    needs_server_state = server is not None or kw.get("dp_clip_norm", 0) > 0
    state_server = server
    if state_server is None and needs_server_state:
        from fedtpu.ops.server_opt import identity_server_optimizer
        state_server = identity_server_optimizer()
    state = init_federated_state(
        jax.random.key(0), mesh, NUM_CLIENTS, init_fn, tx, same_init=True,
        server_opt=state_server,
        shared_start=kw.get("compress", "none") != "none",
        scaffold=kw.get("scaffold", False),
        adaptive_clip_init=(kw["dp_clip_norm"]
                            if kw.get("dp_adaptive_clip") else None))

    step = build_round_fn(mesh, apply_fn, tx, 2, server_opt=server, **kw)
    state, metrics = step(state, batch)
    acc = np.asarray(metrics["client_mean"]["accuracy"])
    assert np.all(np.isfinite(acc))
    # rounds_per_step stacks a leading axis.
    assert acc.shape == ((3,) if kw.get("rounds_per_step") == 3 else ())
    # Every client slot must carry the identical new global.
    for leaf in jax.tree.leaves(state["params"]):
        a = np.asarray(leaf)
        np.testing.assert_allclose(a, np.broadcast_to(a[:1], a.shape),
                                   atol=1e-6)
    # The round counter advanced by the number of rounds executed.
    assert int(np.asarray(state["round"])) == kw.get("rounds_per_step", 1)
