"""Multi-process gang resilience end to end: the chaos matrix's mp_*
rows. Each scenario launches a 2-process jax.distributed gang on CPU
(``fedtpu supervise --num-processes 2``, two virtual devices per
process), injects the fault in-loop (fedtpu.resilience.faults), and
asserts the gang recovered with a per-round metric history bitwise
identical to an uninterrupted gang run — plus the observability half of
the contract: ``gang_restart`` / ``collective_hang`` events must come
back out of ``fedtpu report``'s aggregation.

The baseline is a separate GANG run (reduction order differs across
device counts, so the single-process baseline of
test_chaos_supervised.py is not the right bitwise reference). Each child
is a full CLI training run: this module is excluded from the quick tier
in conftest.py, like test_chaos_supervised.py; the two heaviest rows are
additionally slow-marked (full tier only).
"""

import os
import subprocess
import sys
import time

import pytest

from fedtpu.resilience.chaos import (MP_PROCESSES, _fault_round, _history,
                                     _mp_env, _run_args, run_scenario)
from fedtpu.telemetry.report import aggregate, load_events

ROUNDS = 8
NUM_CLIENTS = 4     # must divide over 2 processes x 2 virtual devices


@pytest.fixture(scope="module")
def gang_env(tmp_path_factory):
    """One uninterrupted 2-process gang baseline shared by every row."""
    wd = str(tmp_path_factory.mktemp("gang"))
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "supervise",
         "--num-processes", str(MP_PROCESSES), "--max-restarts", "0", "--",
         *_run_args(wd, "mp_baseline", ROUNDS, NUM_CLIENTS, "cpu")],
        env=_mp_env(), capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.stderr or "")[-2000:]
    baseline = _history(os.path.join(wd, "mp_baseline.metrics.jsonl"))
    assert sorted(baseline) == list(range(1, ROUNDS + 1))
    return wd, baseline


def _gang_scenario(gang_env, name):
    wd, baseline = gang_env
    row = run_scenario(name, wd, baseline, ROUNDS, NUM_CLIENTS,
                       platform="cpu", timeout=600)
    # The scenario's own verdict: survived, bitwise history match, the
    # fault fired, and at least one all-or-nothing gang restart.
    assert row["ok"], row
    assert row["rc"] == 0 and row["gang_restarts"] >= 1

    # Independent of the verdict logic: recompute the bitwise match and
    # re-read the events through the report aggregation.
    hist = _history(os.path.join(wd, f"{name}.metrics.jsonl"))
    assert hist == baseline             # exact final state vs gang baseline
    events, bad = load_events(os.path.join(wd, f"{name}.events.jsonl"))
    return aggregate(events, malformed=bad)["resilience"]


def test_gang_survives_worker_sigkill(gang_env):
    res = _gang_scenario(gang_env, "mp_kill_worker")
    assert res["gang_restarts"] == 1
    # The kill is abrupt (-9); the healthy peer was torn down with it
    # rather than left blocked in a collective forever.
    assert -9 in res["child_exit_codes"]


def test_gang_survives_collective_hang_in_bounded_time(gang_env):
    t0 = time.time()
    res = _gang_scenario(gang_env, "mp_hang")
    # The wedged worker never reaches a guard; it is a PEER's watchdog
    # that detects the stalled collective, exits 75, and triggers the
    # gang restart — attributed post mortem via the events sink.
    assert res["collective_hangs"], res
    hang = res["collective_hangs"][0]
    assert hang["phase"] in ("dispatch", "chunk_fetch", "eval_fetch",
                             "checkpoint")
    assert hang["waited_s"] >= hang["timeout_s"]
    assert res["gang_restarts"] >= 1
    # Bounded: watchdog timeout (12 s) + teardown grace (10 s) + one
    # restarted run, not the 3600 s the fault sleeps for.
    assert time.time() - t0 < 500


@pytest.mark.slow
def test_gang_survives_coordinator_death_on_a_fresh_port(gang_env):
    res = _gang_scenario(gang_env, "mp_kill_coordinator")
    assert res["gang_restarts"] == 1
    events, _ = load_events(
        os.path.join(gang_env[0], "mp_kill_coordinator.events.jsonl"))
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert g and g[0]["payload"]["coordinator_died"] is True


@pytest.mark.slow
def test_gang_wide_preemption_drains_and_resumes(gang_env):
    res = _gang_scenario(gang_env, "mp_preempt")
    # Every process drained its collective checkpoint and exited 75; the
    # relaunch resumed past the (consumed, once-only) fault round.
    assert 75 in res["child_exit_codes"]
    assert res["preempted_rounds"] == [_fault_round(ROUNDS)]
    events, _ = load_events(
        os.path.join(gang_env[0], "mp_preempt.events.jsonl"))
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert g and g[0]["payload"]["backoff_s"] == 0


@pytest.mark.slow
def test_gang_elastic_shrink_resizes_without_restart(gang_env):
    """The elastic counterpart of mp_preempt: a preemption NOTICE at the
    fault round live-shrinks the gang (victim parks, survivor continues
    the SAME run on the halved mesh) — zero gang restarts, and the
    reshard shows up in the report aggregation with its moved-bytes
    manifest."""
    wd, baseline = gang_env
    row = run_scenario("mp_shrink", wd, baseline, ROUNDS, NUM_CLIENTS,
                       platform="cpu", timeout=600)
    assert row["ok"], row
    assert row["rc"] == 0 and row["gang_restarts"] == 0
    assert row["reshards"] == 1 and row["reshard_failures"] == 0
    events, bad = load_events(os.path.join(wd, "mp_shrink.events.jsonl"))
    res = aggregate(events, malformed=bad)["resilience"]
    r = res["reshards"][0]
    assert r["mode"] == "shrink"
    assert r["target_clients"] == NUM_CLIENTS // 2
    assert r["moved_leaves"] > 0 and r["moved_bytes"] > 0


# ------------------------------------------------- supervisor satellites


def test_supervise_cleans_liveness_and_protocol_residue_on_exit_0(tmp_path):
    """A run that ends EXIT_OK must leave no heartbeat files or
    .agreement/.reshard protocol records behind: a later launch in the
    same workdir would mistake the dead gang's residue for a live or
    resumable one. Round checkpoints survive the sweep."""
    from fedtpu.resilience.distributed import heartbeat_path_for
    from fedtpu.resilience.supervisor import supervise

    ck = tmp_path / "ck"
    for sub in (".agreement", ".reshard", "round_000002"):
        (ck / sub).mkdir(parents=True)
    hb = str(tmp_path / "hb")
    with open(heartbeat_path_for(hb, 0), "w") as fh:
        fh.write("{}")
    rc = supervise(["run", "--checkpoint-dir", str(ck)],
                   max_restarts=0, heartbeat=hb, verbose=False,
                   _cmd_prefix=["/bin/sh", "-c", "exit 0", "sh"])
    assert rc == 0
    assert not os.path.exists(heartbeat_path_for(hb, 0))
    assert not (ck / ".agreement").exists()
    assert not (ck / ".reshard").exists()
    assert (ck / "round_000002").exists()       # checkpoints are kept


def test_supervise_backoff_resets_after_healthy_window(tmp_path):
    """A child that survived past healthy_window starts a NEW incident:
    its crash backs off at base, not at the escalated streak. With the
    window disabled the same crashes escalate exponentially."""
    import json

    from fedtpu.resilience.supervisor import supervise

    def delays(healthy_window):
        ev = str(tmp_path / f"ev{healthy_window}.jsonl")
        rc = supervise(["crash"], max_restarts=3, backoff_base=0.05,
                       backoff_max=10.0, healthy_window=healthy_window,
                       events=ev, verbose=False,
                       _cmd_prefix=["/bin/sh", "-c", "sleep 0.3; exit 7",
                                    "sh"])
        assert rc == 7
        with open(ev) as fh:
            events = [json.loads(ln) for ln in fh if ln.strip()]
        return [e["payload"]["backoff_s"] for e in events
                if e["kind"] == "restart"]

    # 0.3 s child lifetime > 0.2 s window: every crash is a fresh incident.
    assert delays(0.2) == [0.05, 0.05, 0.05]
    # Window disabled: the streak escalates 2^k.
    assert delays(0) == [0.05, 0.1, 0.2]
