"""Multi-process gang resilience end to end: the chaos matrix's mp_*
rows. Each scenario launches a 2-process jax.distributed gang on CPU
(``fedtpu supervise --num-processes 2``, two virtual devices per
process), injects the fault in-loop (fedtpu.resilience.faults), and
asserts the gang recovered with a per-round metric history bitwise
identical to an uninterrupted gang run — plus the observability half of
the contract: ``gang_restart`` / ``collective_hang`` events must come
back out of ``fedtpu report``'s aggregation.

The baseline is a separate GANG run (reduction order differs across
device counts, so the single-process baseline of
test_chaos_supervised.py is not the right bitwise reference). Each child
is a full CLI training run: this module is excluded from the quick tier
in conftest.py, like test_chaos_supervised.py; the two heaviest rows are
additionally slow-marked (full tier only).
"""

import os
import subprocess
import sys
import time

import pytest

from fedtpu.resilience.chaos import (MP_PROCESSES, _fault_round, _history,
                                     _mp_env, _run_args, run_scenario)
from fedtpu.telemetry.report import aggregate, load_events

ROUNDS = 8
NUM_CLIENTS = 4     # must divide over 2 processes x 2 virtual devices


@pytest.fixture(scope="module")
def gang_env(tmp_path_factory):
    """One uninterrupted 2-process gang baseline shared by every row."""
    wd = str(tmp_path_factory.mktemp("gang"))
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "supervise",
         "--num-processes", str(MP_PROCESSES), "--max-restarts", "0", "--",
         *_run_args(wd, "mp_baseline", ROUNDS, NUM_CLIENTS, "cpu")],
        env=_mp_env(), capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, (out.stderr or "")[-2000:]
    baseline = _history(os.path.join(wd, "mp_baseline.metrics.jsonl"))
    assert sorted(baseline) == list(range(1, ROUNDS + 1))
    return wd, baseline


def _gang_scenario(gang_env, name):
    wd, baseline = gang_env
    row = run_scenario(name, wd, baseline, ROUNDS, NUM_CLIENTS,
                       platform="cpu", timeout=600)
    # The scenario's own verdict: survived, bitwise history match, the
    # fault fired, and at least one all-or-nothing gang restart.
    assert row["ok"], row
    assert row["rc"] == 0 and row["gang_restarts"] >= 1

    # Independent of the verdict logic: recompute the bitwise match and
    # re-read the events through the report aggregation.
    hist = _history(os.path.join(wd, f"{name}.metrics.jsonl"))
    assert hist == baseline             # exact final state vs gang baseline
    events, bad = load_events(os.path.join(wd, f"{name}.events.jsonl"))
    return aggregate(events, malformed=bad)["resilience"]


def test_gang_survives_worker_sigkill(gang_env):
    res = _gang_scenario(gang_env, "mp_kill_worker")
    assert res["gang_restarts"] == 1
    # The kill is abrupt (-9); the healthy peer was torn down with it
    # rather than left blocked in a collective forever.
    assert -9 in res["child_exit_codes"]


def test_gang_survives_collective_hang_in_bounded_time(gang_env):
    t0 = time.time()
    res = _gang_scenario(gang_env, "mp_hang")
    # The wedged worker never reaches a guard; it is a PEER's watchdog
    # that detects the stalled collective, exits 75, and triggers the
    # gang restart — attributed post mortem via the events sink.
    assert res["collective_hangs"], res
    hang = res["collective_hangs"][0]
    assert hang["phase"] in ("dispatch", "chunk_fetch", "eval_fetch",
                             "checkpoint")
    assert hang["waited_s"] >= hang["timeout_s"]
    assert res["gang_restarts"] >= 1
    # Bounded: watchdog timeout (12 s) + teardown grace (10 s) + one
    # restarted run, not the 3600 s the fault sleeps for.
    assert time.time() - t0 < 500


@pytest.mark.slow
def test_gang_survives_coordinator_death_on_a_fresh_port(gang_env):
    res = _gang_scenario(gang_env, "mp_kill_coordinator")
    assert res["gang_restarts"] == 1
    events, _ = load_events(
        os.path.join(gang_env[0], "mp_kill_coordinator.events.jsonl"))
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert g and g[0]["payload"]["coordinator_died"] is True


@pytest.mark.slow
def test_gang_wide_preemption_drains_and_resumes(gang_env):
    res = _gang_scenario(gang_env, "mp_preempt")
    # Every process drained its collective checkpoint and exited 75; the
    # relaunch resumed past the (consumed, once-only) fault round.
    assert 75 in res["child_exit_codes"]
    assert res["preempted_rounds"] == [_fault_round(ROUNDS)]
    events, _ = load_events(
        os.path.join(gang_env[0], "mp_preempt.events.jsonl"))
    g = [e for e in events if e["kind"] == "gang_restart"]
    assert g and g[0]["payload"]["backoff_s"] == 0
