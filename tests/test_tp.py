"""2-D ('clients','model') GSPMD engine (fedtpu.parallel.tp): the round
semantics must match the 1-D shard_map engine exactly, with hidden weights
genuinely sharded over the tensor-parallel axis."""

import dataclasses

import jax
import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.orchestration.loop import run_experiment
from fedtpu.parallel import make_mesh, client_sharding, tp
from fedtpu.parallel.round import build_round_fn, init_federated_state

HIDDEN = (16, 8)  # both divisible by the tp extent 2


def _engines(rounds_per_step=1, num_clients=8, hidden=HIDDEN,
             weighting="data_size", seed=3, rows=256):
    """Build the SAME federated setup on both engines (one construction path
    — signature changes to build_round_fn/init_federated_state show up here
    once, for every test)."""
    x, y = synthetic_income_like(rows, 6, 2, seed=seed)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=hidden))
    tx = build_optimizer(OptimConfig())
    key = jax.random.key(3)

    mesh1 = make_mesh(num_clients=num_clients)
    s1 = init_federated_state(key, mesh1, num_clients, init_fn, tx)
    b1 = {k: jax.device_put(v, client_sharding(mesh1)) for k, v in
          {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step1 = build_round_fn(mesh1, apply_fn, tx, 2, weighting=weighting,
                           rounds_per_step=rounds_per_step)

    mesh2 = tp.make_mesh_2d(2, num_clients)
    s2 = tp.init_federated_state_2d(key, mesh2, num_clients, init_fn, tx)
    b2 = {k: jax.device_put(v, tp.batch_sharding_2d(mesh2)) for k, v in
          {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    step2 = tp.build_round_fn_2d(mesh2, apply_fn, tx, 2, weighting=weighting,
                                 rounds_per_step=rounds_per_step)
    return (s1, b1, step1), (s2, b2, step2)


def test_mesh_2d_shape():
    mesh = tp.make_mesh_2d(2, 8)
    assert mesh.axis_names == ("clients", "model")
    assert mesh.devices.shape == (4, 2)


def test_hidden_weights_actually_sharded_over_model():
    mesh = tp.make_mesh_2d(2, 8)
    init_fn, _ = build_model(ModelConfig(input_dim=6, hidden_sizes=HIDDEN))
    tx = build_optimizer(OptimConfig())
    state = tp.init_federated_state_2d(jax.random.key(0), mesh, 8, init_fn, tx)
    w0 = state["params"]["layers"][0]["w"]        # (C, in, h) col-sharded
    shard_shapes = {s.data.shape for s in w0.addressable_shards}
    assert shard_shapes == {(2, 6, HIDDEN[0] // 2)}
    w1 = state["params"]["layers"][1]["w"]        # (C, h, h2) row-sharded
    assert {s.data.shape for s in w1.addressable_shards} == \
        {(2, HIDDEN[0] // 2, HIDDEN[1])}


def test_2d_engine_matches_1d_engine():
    (s1, b1, step1), (s2, b2, step2) = _engines()
    for _ in range(3):
        s1, m1 = step1(s1, b1)
        s2, m2 = step2(s2, b2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=1e-5),
        s1["params"], s2["params"])
    np.testing.assert_allclose(float(m1["client_mean"]["accuracy"]),
                               float(m2["client_mean"]["accuracy"]),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1["per_client"]["f1"]),
                               np.asarray(m2["per_client"]["f1"]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(m1["pooled"]["f1"]),
                               np.asarray(m2["pooled"]["f1"]), atol=1e-6)


def test_2d_engine_multi_round_scan():
    (_, _, _), (s2, b2, step2) = _engines(rounds_per_step=4)
    s2, m2 = step2(s2, b2)
    assert np.asarray(m2["client_mean"]["accuracy"]).shape == (4,)
    assert int(s2["round"]) == 4


@pytest.mark.parametrize("hidden,clients,weighting", [
    ((16,), 4, "data_size"),          # single hidden layer (col then logits)
    ((16, 8), 8, "uniform"),          # even depth, uniform averaging
    ((16, 8, 4), 8, "data_size"),     # odd depth: ends col-sharded pre-logits
])
def test_engines_agree_across_configs(hidden, clients, weighting):
    """Config-sweep contract: for any depth/clients/weighting combo the 1-D
    shard_map engine and the 2-D GSPMD engine produce the same params."""
    (s1, b1, step1), (s2, b2, step2) = _engines(
        num_clients=clients, hidden=hidden, weighting=weighting,
        seed=clients, rows=32 * clients)
    for _ in range(2):
        s1, m1 = step1(s1, b1)
        s2, m2 = step2(s2, b2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=1e-5),
        s1["params"], s2["params"])
    np.testing.assert_allclose(np.asarray(m1["per_client"]["accuracy"]),
                               np.asarray(m2["per_client"]["accuracy"]),
                               atol=1e-6)


def test_convnet_engines_agree():
    """ConvNet on the 2-D mesh: conv kernels channel-shard over 'model' and
    the round must match the 1-D engine."""
    from fedtpu.data.cifar10 import synthetic_cifar_like
    x, y = synthetic_cifar_like(64, seed=4, image_shape=(8, 8, 3), classes=4)
    x = x.reshape(64, -1)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    model_cfg = ModelConfig(kind="convnet", image_shape=(8, 8, 3),
                            conv_channels=(4, 8), hidden_sizes=(16,),
                            num_classes=4)
    init_fn, apply_fn = build_model(model_cfg)
    tx = build_optimizer(OptimConfig())
    key = jax.random.key(9)
    feed = {"x": packed.x, "y": packed.y, "mask": packed.mask}

    mesh1 = make_mesh(num_clients=8)
    s1 = init_federated_state(key, mesh1, 8, init_fn, tx)
    b1 = {k: jax.device_put(v, client_sharding(mesh1)) for k, v in feed.items()}
    step1 = build_round_fn(mesh1, apply_fn, tx, 4)

    mesh2 = tp.make_mesh_2d(2, 8)
    s2 = tp.init_federated_state_2d(key, mesh2, 8, init_fn, tx)
    b2 = {k: jax.device_put(v, tp.batch_sharding_2d(mesh2))
          for k, v in feed.items()}
    step2 = tp.build_round_fn_2d(mesh2, apply_fn, tx, 4)

    # Conv kernels really are channel-sharded over 'model'.
    w0 = s2["params"]["convs"][0]["w"]          # (C, 3, 3, 3, 4) col-sharded
    assert {s.data.shape for s in w0.addressable_shards} == {(2, 3, 3, 3, 2)}
    w1 = s2["params"]["convs"][1]["w"]          # (C, 3, 3, 4, 8) row-sharded
    assert {s.data.shape for s in w1.addressable_shards} == {(2, 3, 3, 2, 8)}

    for _ in range(2):
        s1, m1 = step1(s1, b1)
        s2, m2 = step2(s2, b2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=2e-5, atol=1e-5),
        s1["params"], s2["params"])
    np.testing.assert_allclose(np.asarray(m1["per_client"]["accuracy"]),
                               np.asarray(m2["per_client"]["accuracy"]),
                               atol=1e-6)


def test_checkpoint_resume_preserves_tp_layout(tmp_path):
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        model=ModelConfig(hidden_sizes=HIDDEN),
        fed=FedConfig(rounds=2),
        run=RunConfig(model_parallel=2, checkpoint_dir=str(tmp_path),
                      checkpoint_every=1),
    )
    run_experiment(cfg, verbose=False)
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.orchestration.checkpoint import load_checkpoint
    exp = build_experiment(cfg)
    state, _, step = load_checkpoint(str(tmp_path), state_like=exp.state)
    assert step == 2
    w0 = state["params"]["layers"][0]["w"]
    # The column-sharded hidden weight must come back model-sharded, not
    # replicated over the model axis.
    assert {s.data.shape for s in w0.addressable_shards} == \
        {(2, w0.shape[1], HIDDEN[0] // 2)}
    # And resume must run on from it.
    res = run_experiment(cfg, verbose=False, resume=True)
    assert res.rounds_run == 2


def test_unsupported_combos_raise():
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128),
        shard=ShardConfig(num_clients=8),
        model=ModelConfig(hidden_sizes=HIDDEN),
        fed=FedConfig(rounds=1),
        run=RunConfig(model_parallel=2),
    )
    from fedtpu.orchestration.loop import build_experiment
    with pytest.raises(ValueError, match="ring"):
        build_experiment(dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, aggregation="ring")))
    with pytest.raises(ValueError, match="divisible"):
        build_experiment(dataclasses.replace(
            base, model=dataclasses.replace(base.model,
                                            hidden_sizes=(25, 16))))
    # Odd-index dims are never placed on the model axis (row layers shard
    # the previous out-dim), so (50, 25) is a VALID layout at tp=2.
    build_experiment(dataclasses.replace(
        base, model=dataclasses.replace(base.model, hidden_sizes=(50, 25))))


def test_run_experiment_model_parallel():
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        model=ModelConfig(hidden_sizes=HIDDEN),
        fed=FedConfig(rounds=3),
        run=RunConfig(model_parallel=2),
    )
    res = run_experiment(cfg, verbose=False)
    base = run_experiment(
        dataclasses.replace(cfg, run=RunConfig(model_parallel=1)),
        verbose=False)
    np.testing.assert_allclose(res.global_metrics["accuracy"],
                               base.global_metrics["accuracy"], atol=1e-6)


def test_per_device_state_bytes_scale_down_with_tp():
    """The 2-D engine's reason to exist (benchmarks/tp_memory.py pins the
    full-size numbers): measured per-device params+opt bytes drop ~1/tp
    for a fixed federation as chips-per-client grow. Slack below the
    ideal 2x/4x is the model-replicated logits head and row biases."""
    from fedtpu.utils.trees import max_device_bytes

    init_fn, _ = build_model(ModelConfig(input_dim=64,
                                         hidden_sizes=(256, 256)))
    tx = build_optimizer(OptimConfig())

    def state_bytes(state):
        return max_device_bytes({"p": state["params"],
                                 "o": state["opt_state"]})

    mesh1 = make_mesh(num_devices=2, num_clients=2)
    base = state_bytes(
        init_federated_state(jax.random.key(0), mesh1, 2, init_fn, tx))
    for mp, floor in ((2, 1.8), (4, 3.6)):
        mesh2 = tp.make_mesh_2d(mp, 2)
        b = state_bytes(tp.init_federated_state_2d(
            jax.random.key(0), mesh2, 2, init_fn, tx))
        assert base / b > floor, (mp, base, b)


def test_bare_leaf_params_rejected():
    """Advisor r4: a single-leaf params pytree ('*' treedef) would match
    EVERY optimizer-state subtree in place_opt and assign 2-D param
    shardings to scalar step counts. The init must refuse it up front."""
    mesh = tp.make_mesh_2d(2, 8)
    tx = build_optimizer(OptimConfig())
    with pytest.raises(ValueError, match="dict params pytree"):
        tp.init_federated_state_2d(
            jax.random.key(0), mesh, 8,
            lambda k: jax.random.normal(k, (6, 4)), tx)
