"""fedtpu.serving — admission control, traces, the serving engine, and
the socket path (ISSUE 6 tier-1 suite).

Pins the contracts the serving front-end documents:
- admission verdict ORDER (rate -> backpressure -> staleness -> accept);
- the versioned trace schema round-trips and synthesis is deterministic;
- replaying the same trace + seed yields a BITWISE-identical per-tick
  metric history (virtual-time determinism, the acceptance criterion);
- checkpoint/restore mid-stream continues to the identical history and
  global params as an uninterrupted run (the graceful-drain satellite);
- drain-time K-buffer starvation surfaces as the PR 5 async_starvation
  event;
- a real localhost serve + loadgen round trip works end to end;
- the report pipeline renders the serving section from serve events.

Subprocess SIGTERM/bench coverage is `slow`-marked (full tier only).
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from fedtpu.config import ServingConfig
from fedtpu.serving.admission import (ACCEPT, DEPRIORITIZE,
                                      REJECT_BACKPRESSURE, REJECT_RATE,
                                      REJECT_STALE, SCREENED, VERDICTS,
                                      AdmissionController, AdmissionPolicy,
                                      TokenBucket)
from fedtpu.serving.traces import (TRACE_SCHEMA_VERSION, load_trace_arrays,
                                   read_trace, synthesize_trace,
                                   write_trace)
from fedtpu.telemetry.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- admission

def test_token_bucket_rate_and_refill():
    tb = TokenBucket(rate=2.0, burst=2.0)
    assert tb.take(0.0) and tb.take(0.0)
    assert not tb.take(0.0)            # burst exhausted
    assert tb.take(0.5)                # 0.5 virtual s => 1 token back
    assert not tb.take(0.5)
    # rate 0 disables limiting entirely.
    free = TokenBucket(rate=0.0, burst=1.0)
    assert all(free.take(0.0) for _ in range(100))


def test_admission_check_order_is_rate_backpressure_staleness():
    """The documented precedence: a single update violating EVERY
    constraint is billed to the rate limiter; with rate available, to
    backpressure; then staleness; then accepted."""
    pol = AdmissionPolicy(rate_limit=0.1, rate_burst=1.0, max_pending=4,
                          stale_deprioritize=2, stale_reject=8)
    ctl = AdmissionController(pol, registry=MetricsRegistry())
    # Burn the single burst token on a clean accept.
    assert ctl.decide(0.0, staleness=0, pending=0) == ACCEPT
    # Everything wrong at once, bucket empty -> rate wins.
    assert ctl.decide(0.0, staleness=99, pending=99) == REJECT_RATE
    # One token refilled (10 virtual s at 0.1/s), pending full ->
    # backpressure wins over staleness.
    assert ctl.decide(10.0, staleness=99, pending=99) == REJECT_BACKPRESSURE
    # Rate + pending fine, staleness strictly above the reject bar.
    assert ctl.decide(20.0, staleness=9, pending=0) == REJECT_STALE
    # Between the two staleness bars -> admitted but deprioritized.
    assert ctl.decide(30.0, staleness=3, pending=0) == DEPRIORITIZE
    assert ctl.decide(40.0, staleness=0, pending=0) == ACCEPT
    # The defense verdict never comes from decide() — it is recorded by
    # the engine's screen/quarantine path through record().
    assert ctl.record(SCREENED, 50.0) == SCREENED
    with pytest.raises(ValueError, match="unknown verdict"):
        ctl.record("bogus")
    # Every verdict was exercised and counted (both dict + registry).
    assert set(ctl.counts) == set(VERDICTS)
    assert all(n >= 1 for n in ctl.counts.values())


def test_admission_policy_validates_thresholds():
    with pytest.raises(ValueError):
        AdmissionPolicy(stale_deprioritize=8, stale_reject=4)


# ------------------------------------------------------------------- traces

def test_trace_roundtrip_and_header(tmp_path):
    header, t, user, lat = synthesize_trace(users=10_000, arrivals=500,
                                            horizon_s=30.0, seed=7)
    assert header.v == TRACE_SCHEMA_VERSION
    assert header.users == 10_000 and header.arrivals == 500
    assert np.all(np.diff(t) >= 0)          # sorted virtual time
    assert np.all(lat <= t)                 # pull happened after t=0
    path = tmp_path / "trace.jsonl"
    write_trace(str(path), header, t, user, lat)

    h2, events = read_trace(str(path))
    assert h2.to_json() == header.to_json()
    rows = list(events)
    assert len(rows) == 500
    assert [e.user for e in rows] == user.tolist()
    np.testing.assert_allclose([e.t for e in rows], t, rtol=0, atol=1e-9)

    h3, t3, u3, l3 = load_trace_arrays(str(path))
    np.testing.assert_array_equal(u3, user)
    np.testing.assert_allclose(t3, t, rtol=0, atol=1e-9)
    np.testing.assert_allclose(l3, lat, rtol=0, atol=1e-9)


def test_trace_rejects_unknown_schema_version(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text('{"kind": "header", "v": 99}\n')
    with pytest.raises(ValueError):
        read_trace(str(path))


def test_trace_synthesis_is_deterministic():
    a = synthesize_trace(users=1000, arrivals=200, seed=3)
    b = synthesize_trace(users=1000, arrivals=200, seed=3)
    c = synthesize_trace(users=1000, arrivals=200, seed=4)
    for x, y in zip(a[1:], b[1:]):
        np.testing.assert_array_equal(x, y)
    assert not np.array_equal(a[2], c[2])


# ------------------------------------------------------------------- engine

def _small_cfg(**kw):
    base = dict(cohort=8, buffer_size=2, tick_interval_s=0.5,
                data_rows=64, model_hidden=(8,), seed=0)
    base.update(kw)
    return ServingConfig(**base)


def _small_trace(arrivals=200, seed=11):
    return synthesize_trace(users=500, arrivals=arrivals, horizon_s=10.0,
                            seed=seed)


def _replay(engine, t, user, lat):
    engine.offer_many(zip(user.tolist(), t.tolist(), lat.tolist()))
    engine.drain()
    return engine


def test_engine_replay_is_bitwise_deterministic():
    from fedtpu.serving.engine import ServingEngine
    _, t, user, lat = _small_trace()
    lines = []
    for _ in range(2):
        eng = _replay(ServingEngine(_small_cfg(),
                                    registry=MetricsRegistry()),
                      t, user, lat)
        lines.append(eng.history_lines())
    assert lines[0] == lines[1]
    assert len(lines[0]) >= 10              # ticks actually fired


def test_engine_coalesces_same_slot_arrivals():
    """Multiple queued updates from one USER ride one tick as ONE
    arrival — tick_updates counts updates, tick_slots counts slots.
    (Slot coalescing is per user now: distinct users get distinct slots
    via the binder, so only repeat arrivals from the same user share.)"""
    from fedtpu.serving.engine import ServingEngine
    eng = ServingEngine(_small_cfg(cohort=4, tick_interval_s=0.0),
                        registry=MetricsRegistry())
    # user 0 twice + user 1 once: two slots, three updates.
    for u in (0, 0, 1):
        assert eng.offer(0.1, u, 0.0) == ACCEPT
    eng.drain()
    assert eng.history["tick_updates"][-1] == 3
    assert eng.history["tick_slots"][-1] == 2


def test_distinct_users_never_alias_onto_one_slot():
    """Regression for the residue-map bug the binder replaced: users 0
    and 4 with cohort=4 used to both train slot 0 (`user % C`), silently
    merging two client identities. Stable binding gives them distinct
    slots while capacity lasts."""
    from fedtpu.serving.engine import ServingEngine
    eng = ServingEngine(_small_cfg(cohort=4, tick_interval_s=0.0),
                        registry=MetricsRegistry())
    for u in (0, 4):
        assert eng.offer(0.1, u, 0.0) == ACCEPT
    eng.drain()
    assert eng.binder.peek(0) != eng.binder.peek(4)
    assert eng.history["tick_updates"][-1] == 2
    assert eng.history["tick_slots"][-1] == 2    # was 1 under `u % C`


def test_deprioritized_updates_wait_an_extra_tick():
    from fedtpu.serving.engine import ServingEngine
    eng = ServingEngine(_small_cfg(buffer_size=0, tick_interval_s=0.0,
                                   flush_every=1, stale_deprioritize=0,
                                   stale_reject=16),
                        registry=MetricsRegistry())
    # flush_every=1 with M=0: the accept fires a tick and bumps the
    # version, so the next arrival claiming version 0 is one stale.
    assert eng.offer(0.1, 1, 0.0) == ACCEPT
    assert eng.version == 1
    assert eng.offer(0.2, 2, 0.0, version=0) == DEPRIORITIZE
    assert eng.pending[0].elig_tick == eng.tick_count + 2


def test_stats_and_drain_on_idle_engine_do_not_crash():
    """REVIEW fix (high): a 'stats' request — or the SIGTERM/--once
    drain path — before any update is incorporated must answer with a
    None latency section, not IndexError out of _percentiles (which
    killed the whole single-threaded server and broke the
    drain->checkpoint->exit-75 contract for idle shutdowns)."""
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.serving.server import _handle

    eng = ServingEngine(_small_cfg(), registry=MetricsRegistry())
    resp = _handle(eng, {"op": "stats"})
    assert resp["op"] == "stats"
    assert resp["update_to_incorporation"] is None
    # The idle-shutdown sequence: drain, then the summary emission that
    # precedes the history write + checkpoint in _shutdown.
    assert eng.drain() == 0
    s = eng.emit_summary()
    assert s["update_to_incorporation"] is None and s["incorporated"] == 0


def test_handler_exception_becomes_error_frame():
    """REVIEW fix (low): an unexpected exception inside request handling
    answers an ``error`` frame and counts serve_handler_errors instead
    of escaping and killing the server for every connection."""
    from fedtpu.serving.server import _safe_handle
    from fedtpu.telemetry.trace import NullTracer

    reg = MetricsRegistry()
    # engine=None: any real op dereferences it and raises AttributeError,
    # standing in for an arbitrary internal failure.
    resp = _safe_handle(None, {"op": "stats"}, NullTracer(), reg)
    assert resp["op"] == "error" and "AttributeError" in resp["reason"]
    assert reg.snapshot()["counters"]["serve_handler_errors"] == 1
    # Malformed frames still answer without touching the engine.
    assert _safe_handle(None, None, NullTracer(), reg)["op"] == "error"


def test_engine_checkpoint_restore_is_bitwise(tmp_path):
    """Drain-to-checkpoint at half-stream, restore into a FRESH engine,
    replay the rest: history and global params must match the
    uninterrupted run exactly (the supervise-restart contract)."""
    import jax

    from fedtpu.serving.engine import ServingEngine
    _, t, user, lat = _small_trace(arrivals=120)
    half = 60

    ref = _replay(ServingEngine(_small_cfg(), registry=MetricsRegistry()),
                  t, user, lat)

    eng1 = ServingEngine(_small_cfg(), registry=MetricsRegistry())
    eng1.offer_many(zip(user[:half].tolist(), t[:half].tolist(),
                        lat[:half].tolist()))
    eng1.checkpoint(str(tmp_path))

    eng2 = ServingEngine(_small_cfg(), registry=MetricsRegistry())
    eng2.restore(str(tmp_path))
    _replay(eng2, t[half:], user[half:], lat[half:])

    assert eng2.history_lines() == ref.history_lines()
    for a, b in zip(jax.tree.leaves(eng2.state["params"]),
                    jax.tree.leaves(ref.state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restores_admission_and_latency_state(tmp_path):
    """REVIEW fix (medium): the checkpoint carries token-bucket fill,
    per-verdict counts, and latency telemetry — so with rate limiting ON
    a resumed run produces the same verdict sequence, summary counts,
    and percentiles as an uninterrupted one (a fresh bucket would refill
    to full burst and diverge)."""
    from fedtpu.serving.engine import ServingEngine
    cfg = _small_cfg(rate_limit=4.0, rate_burst=2.0)
    _, t, user, lat = _small_trace(arrivals=120)
    half = 60

    ref = _replay(ServingEngine(cfg, registry=MetricsRegistry()),
                  t, user, lat)
    assert ref.admission.counts[REJECT_RATE] > 0   # the limiter did bite

    eng1 = ServingEngine(cfg, registry=MetricsRegistry())
    eng1.offer_many(zip(user[:half].tolist(), t[:half].tolist(),
                        lat[:half].tolist()))
    eng1.checkpoint(str(tmp_path))

    reg2 = MetricsRegistry()
    eng2 = ServingEngine(cfg, registry=reg2)
    eng2.restore(str(tmp_path))
    _replay(eng2, t[half:], user[half:], lat[half:])

    assert eng2.history_lines() == ref.history_lines()
    assert eng2.admission.counts == ref.admission.counts
    assert eng2.latencies == ref.latencies
    s_ref, s2 = ref.summary(), eng2.summary()
    assert s2["update_to_incorporation"] == s_ref["update_to_incorporation"]
    assert s2["admission"] == s_ref["admission"]
    # Histogram + registry instruments cover the WHOLE run post-resume.
    assert eng2._lat_hist.count == ref._lat_hist.count
    assert eng2._lat_hist.bucket_counts == ref._lat_hist.bucket_counts
    counters = reg2.snapshot()["counters"]
    assert counters["serve_updates_incorporated"] == ref.incorporated
    assert counters["admission_" + REJECT_RATE] == \
        ref.admission.counts[REJECT_RATE]


def test_latency_apply_log_and_history_stay_bounded(monkeypatch):
    """REVIEW fix (low): the exact-latency list and the apply log are
    windowed (full distribution lives in the cumulative histogram), and
    --history-window bounds the per-tick history — a long-running server
    must not grow host memory per incorporated update forever."""
    from fedtpu.serving import engine as engine_mod
    from fedtpu.serving.engine import ServingEngine

    monkeypatch.setattr(engine_mod, "LATENCY_WINDOW", 32)
    monkeypatch.setattr(engine_mod, "_APPLIES_MAX", 16)
    monkeypatch.setattr(engine_mod, "_APPLIES_KEEP", 8)
    eng = ServingEngine(
        _small_cfg(buffer_size=0, tick_interval_s=0.0, flush_every=1,
                   stale_deprioritize=2, stale_reject=4,
                   history_window=10),
        registry=MetricsRegistry())
    # Every arrival fires one tick and one apply (M=0): 100 applies.
    for i in range(100):
        assert eng.offer(0.1 * (i + 1), i, 0.0) == ACCEPT
    assert eng.incorporated == 100
    assert len(eng.latencies) <= 32
    assert eng._lat_hist.count == 100                 # full distribution
    assert len(eng._applies_t) <= 16
    # Recent lookups are untouched by compaction.
    assert eng.pulled_version(eng.clock.now) == eng.version == 100
    assert len(eng.history["tick_t"]) == 10
    assert eng.history["tick_version"][-1] == 100


def test_drain_flags_kbuffer_starvation():
    """Fewer buffered updates than the K-buffer needs to apply -> the
    PR 5 async_starvation event fires as an SLO signal at drain."""
    from fedtpu.serving.engine import ServingEngine
    reg = MetricsRegistry()
    eng = ServingEngine(_small_cfg(buffer_size=4, tick_interval_s=0.0),
                        registry=reg)
    eng.offer(0.1, 1, 0.0)
    eng.offer(0.2, 2, 0.0)
    eng.drain()
    assert eng.version == 0                 # never reached an apply
    assert reg.snapshot()["counters"]["async_starvation_events"] == 1


def test_summary_has_slo_sections():
    from fedtpu.serving.engine import ServingEngine
    _, t, user, lat = _small_trace(arrivals=80)
    eng = _replay(ServingEngine(_small_cfg(), registry=MetricsRegistry()),
                  t, user, lat)
    s = eng.summary()
    pct = s["update_to_incorporation"]
    assert set(pct) >= {"p50_s", "p90_s", "p99_s", "mean_s", "max_s"}
    assert 0.0 <= pct["p50_s"] <= pct["p99_s"] <= pct["max_s"]
    assert s["incorporated"] > 0 and s["ticks"] > 0
    assert s["rounds_per_sec"] > 0
    assert sum(s["admission"].values()) == 80


# -------------------------------------------------------------- socket path

def test_serve_loadgen_localhost_smoke(tmp_path):
    """Full wire path in-process: run_server (thread, once=True) fed by
    the loadgen replaying a written trace over localhost TCP."""
    from fedtpu.serving.loadgen import run_loadgen
    from fedtpu.serving.server import run_server

    header, t, user, lat = _small_trace(arrivals=150)
    trace = tmp_path / "trace.jsonl"
    write_trace(str(trace), header, t, user, lat)
    pf = tmp_path / "port"

    th = threading.Thread(
        target=run_server,
        kwargs=dict(cfg=_small_cfg(), port_file=str(pf), once=True,
                    history_path=str(tmp_path / "hist.jsonl"),
                    verbose=False))
    th.start()
    try:
        res = run_loadgen(str(trace), port_file=str(pf), batch=64)
    finally:
        th.join(timeout=60)
    assert not th.is_alive()
    assert res["events_sent"] == 150
    assert sum(res["admission"].values()) == 150
    stats = res["server_stats"]
    assert stats["ticks"] > 0 and stats["incorporated"] > 0
    # The server wrote its deterministic per-tick history on shutdown.
    hist = (tmp_path / "hist.jsonl").read_text().strip().splitlines()
    assert len(hist) == stats["ticks"]
    assert json.loads(hist[-1])["tick_version"] == stats["version"]


def test_protocol_rejects_version_mismatch_and_keeps_connection():
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.serving.server import _handle

    eng = ServingEngine(_small_cfg(), registry=MetricsRegistry())
    bad = _handle(eng, {"op": "hello", "v": 99})
    assert bad["op"] == "error"
    ok = _handle(eng, {"op": "hello", "v": 1})
    assert ok["op"] == "welcome" and ok["cohort"] == eng.C
    # Unknown op answers an error frame, never raises.
    assert _handle(eng, {"op": "nope"})["op"] == "error"


# ------------------------------------------------------------------- report

def test_report_renders_serving_section(tmp_path):
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.telemetry.report import render_report
    from fedtpu.telemetry.trace import Tracer

    events = tmp_path / "events.jsonl"
    tracer = Tracer(str(events))
    _, t, user, lat = _small_trace(arrivals=100)
    eng = ServingEngine(_small_cfg(buffer_size=4),
                        registry=MetricsRegistry(), tracer=tracer)
    _replay(eng, t, user, lat)
    eng.emit_summary()
    # A second, starved engine on the same sink: two buffered updates
    # never reach the M=4 apply, so the drain emits async_starvation.
    starved = ServingEngine(_small_cfg(buffer_size=4,
                                       tick_interval_s=0.0),
                            registry=MetricsRegistry(), tracer=tracer)
    starved.offer(0.1, 1, 0.0)
    starved.offer(0.2, 2, 0.0)
    starved.drain()
    tracer.close()

    text, prom = render_report(str(events), fmt="text")
    assert "SERVING" in text.upper()
    assert "update_to_incorporation" in text
    assert "rounds/sec" in text
    assert "STARVATION" in text
    assert "fedtpu_update_to_incorporation_seconds" in prom
    assert 'quantile="0.99"' in prom
    assert "fedtpu_admission_accept_total" in prom
    assert "fedtpu_serve_ticks_total" in prom


# -------------------------------------------------- subprocess (full tier)

@pytest.mark.slow
def test_serve_sigterm_drains_checkpoints_and_exits_75(tmp_path):
    """SIGTERM mid-serve: drain, checkpoint, exit EXIT_PREEMPTED (75) —
    the supervise-compatible graceful preemption contract."""
    import signal

    from fedtpu.serving.loadgen import run_loadgen

    pf = tmp_path / "port"
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fedtpu.cli", "serve", "--platform", "cpu",
         "--port-file", str(pf), "--buffer-size", "2",
         "--checkpoint-dir", str(ckpt),
         "--events", str(tmp_path / "events.jsonl"), "--quiet"],
        cwd=REPO, env=env)
    try:
        header, t, user, lat = _small_trace(arrivals=100)
        trace = tmp_path / "trace.jsonl"
        write_trace(str(trace), header, t, user, lat)
        run_loadgen(str(trace), port_file=str(pf), drain=False)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 75
    rounds = [p for p in os.listdir(ckpt) if p.startswith("round_")]
    assert rounds, "SIGTERM drain wrote no checkpoint"


@pytest.mark.slow
def test_serving_bench_small_artifact(tmp_path):
    """serving_bench end to end at toy scale: both rows present, SLO
    keys populated, artifact is valid JSONL."""
    out = tmp_path / "bench.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "benchmarks/serving_bench.py", "--users", "50000",
         "--arrivals", "5000", "--socket-events", "1000",
         "--json", str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570)
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    kinds = {row["row"] for row in rows}
    assert kinds == {"serving_inproc", "serving_socket"}
    inproc = next(row for row in rows if row["row"] == "serving_inproc")
    assert inproc["update_to_incorporation"]["p99_s"] > 0
    assert inproc["rounds_per_sec"] > 0
    # +1: the bench admits one warm-up offer before the timed replay.
    assert sum(inproc["admission"].values()) == 5000 + 1
