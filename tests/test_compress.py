"""int8-quantized update exchange (fedtpu.parallel.compress): unit error
bounds + end-to-end parity with the exact f32 averaging path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.compress import dequantize, quantize_tensor
from fedtpu.parallel.round import build_round_fn, init_federated_state


# ---------------------------------------------------------------- unit level

def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.normal(size=(37, 5)).astype(np.float32))
    q, scale = quantize_tensor(d)
    assert q.dtype == jnp.int8
    back = dequantize(q, scale)
    # Round-to-nearest: error <= scale/2 per element.
    err = np.abs(np.asarray(back) - np.asarray(d))
    assert np.all(err <= float(scale) / 2 * (1 + 1e-6))


def test_quantize_zero_delta_is_exact():
    d = jnp.zeros((3, 8))
    q, scale = quantize_tensor(d)
    assert float(scale) == 0.0
    np.testing.assert_array_equal(np.asarray(dequantize(q, scale)), 0.0)


def test_quantize_preserves_extremes():
    # The max-magnitude element maps to exactly +-127 and dequantizes back
    # to its original value.
    d = jnp.asarray([0.5, -2.0, 1.0])
    q, scale = quantize_tensor(d)
    assert int(q[1]) == -127
    back = np.asarray(dequantize(q, scale))
    np.testing.assert_allclose(back[1], -2.0, rtol=1e-6)


def test_dequantize_broadcasts_gathered_scales():
    # Gathered payloads carry a leading device axis on q AND scale.
    q = jnp.asarray([[10, -20], [30, 40]], jnp.int8)
    scale = jnp.asarray([0.1, 0.2])
    out = np.asarray(dequantize(q, scale))
    np.testing.assert_allclose(out, [[1.0, -2.0], [6.0, 8.0]], rtol=1e-6)


# ----------------------------------------------------------- round-fn level

def _setup(compress="none", num_clients=8, rows=200, lr=0.004, **round_kw):
    x, y = synthetic_income_like(rows, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=num_clients,
                                            shuffle=False))
    mesh = make_mesh(num_clients=num_clients)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=lr))
    state = init_federated_state(jax.random.key(1), mesh, num_clients,
                                 init_fn, tx, same_init=True,
                                 shared_start=compress != "none")
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    round_step = build_round_fn(mesh, apply_fn, tx, 2, compress=compress,
                                **round_kw)
    return state, batch, round_step


def test_compressed_round_tracks_exact_averaging():
    exact_state, batch, exact_step = _setup(compress="none")
    q_state, _, q_step = _setup(compress="int8")
    for _ in range(5):
        exact_state, em = exact_step(exact_state, batch)
        q_state, qm = q_step(q_state, batch)
    # Per-round quantization error is <= half an int8 step of the largest
    # delta element; after 5 rounds the params should still track closely.
    for a, b in zip(jax.tree.leaves(exact_state["params"]),
                    jax.tree.leaves(q_state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
    assert abs(float(em["client_mean"]["accuracy"])
               - float(qm["client_mean"]["accuracy"])) < 0.05


def test_compressed_zero_lr_is_bit_exact():
    # lr=0 -> all deltas are exactly zero -> quantization is lossless and
    # the round is a no-op on params.
    state, batch, step = _setup(compress="int8", lr=0.0)
    before = jax.tree.map(np.asarray, jax.device_get(state["params"]))
    state, _ = step(state, batch)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 before, state["params"])


def test_compressed_inside_multi_round_scan():
    state, batch, step = _setup(compress="int8", rounds_per_step=3)
    state, metrics = step(state, batch)
    assert metrics["client_mean"]["accuracy"].shape == (3,)
    assert int(state["round"]) == 3


def test_compressed_with_participation_sampling():
    state, batch, step = _setup(compress="int8", participation_rate=0.5)
    for _ in range(3):
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["client_mean"]["accuracy"]))
    # Slots stay identical (the broadcast global) under sampling too.
    p = np.asarray(jax.tree.leaves(state["params"])[0])
    np.testing.assert_allclose(p, np.broadcast_to(p[:1], p.shape), atol=0)


def test_compress_rejects_delta_path_and_ring():
    from fedtpu.ops.server_opt import make_server_optimizer
    with pytest.raises(ValueError, match="plain averaging only"):
        _setup(compress="int8", server_opt=make_server_optimizer("fedadam"))
    with pytest.raises(ValueError, match="psum"):
        _setup(compress="int8", aggregation="ring")
    with pytest.raises(ValueError, match="unknown compress"):
        _setup(compress="int4")


def test_compress_rejects_state_without_shared_start():
    # start + mean(delta) is only the weighted mean when all slots start at
    # the shared global; a plain state must be refused, not silently wrong.
    plain_state, batch, _ = _setup(compress="none")
    _, _, q_step = _setup(compress="int8")
    with pytest.raises(ValueError, match="shared_start"):
        q_step(plain_state, batch)


# ------------------------------------------------------------ loop-level e2e

def test_run_experiment_with_compression():
    from fedtpu.orchestration.loop import run_experiment
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=8, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        optim=OptimConfig(),
        fed=FedConfig(rounds=6, compress="int8"),
        run=RunConfig(rounds_per_step=2),
    )
    result = run_experiment(cfg, verbose=False)
    assert result.rounds_run == 6
    assert all(np.isfinite(v) for v in result.global_metrics["accuracy"])


def test_2d_engine_rejects_compression():
    from fedtpu.orchestration.loop import build_experiment
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=128,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(compress="int8"),
        run=RunConfig(model_parallel=2),
    )
    with pytest.raises(ValueError, match="1-D engine"):
        build_experiment(cfg)
