"""Regression tests for review findings: numeric-label re-encoding,
empty-shard metric masking, and experiment resume."""

import numpy as np
import pandas as pd

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig, RunConfig,
                           ShardConfig)
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.orchestration.loop import run_experiment


def test_numeric_labels_reencoded_to_contiguous_indices(tmp_path):
    # Label values {1, 2} (like a diabetes 'Outcome' column) must map to
    # class indices {0, 1}, not be used as raw indices.
    rng = np.random.default_rng(0)
    df = pd.DataFrame({
        "a": rng.normal(size=200),
        "b": rng.normal(size=200),
        "Outcome": np.where(np.arange(200) % 2 == 0, 1, 2),
    })
    path = tmp_path / "d.csv"
    df.to_csv(path, index=False)
    ds = load_tabular_dataset(DataConfig(csv_path=str(path),
                                         label_column="Outcome"))
    assert ds.num_classes == 2
    assert set(np.unique(ds.y_train)) <= {0, 1}
    assert ds.label_classes.tolist() == [1, 2]


def test_empty_shards_excluded_from_client_mean():
    # 5 rows -> 4 train samples after the 80/20 split; contiguous chunking
    # gives clients 0-3 one sample each and 4-7 none.
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=5),
        shard=ShardConfig(num_clients=8, shuffle=False),
        fed=FedConfig(rounds=1),
    )
    res = run_experiment(cfg, verbose=False)
    acc = res.global_metrics["accuracy"][0]
    per_client = res.per_client_metrics["accuracy"][0]
    # Mean over NON-EMPTY clients only; with 1 sample each, per-client
    # accuracy is 0 or 1, so the mean must be attainable from 4 clients.
    assert acc in {0.0, 0.25, 0.5, 0.75, 1.0}
    # Empty clients report 0 but don't drag the mean below the true value.
    nonempty_mean = per_client[:4].mean()
    np.testing.assert_allclose(acc, nonempty_mean, atol=1e-6)


def test_resume_continues_from_checkpoint(tmp_path):
    ckdir = str(tmp_path / "ck")
    base = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256),
        shard=ShardConfig(num_clients=8),
        run=RunConfig(checkpoint_dir=ckdir, checkpoint_every=2),
    )
    first = run_experiment(base.replace(fed=FedConfig(rounds=4)),
                           verbose=False)
    assert first.rounds_run == 4

    resumed = run_experiment(base.replace(fed=FedConfig(rounds=6)),
                             verbose=False, resume=True)
    # Started at round 4, ran 2 more; history covers all 6 rounds.
    assert resumed.rounds_run == 6
    assert len(resumed.global_metrics["accuracy"]) == 6
    # The restored prefix matches the first run's history.
    np.testing.assert_allclose(resumed.global_metrics["accuracy"][:4],
                               first.global_metrics["accuracy"][:4])
