"""Multi-process end-to-end test of the DCN path: two OS processes, four
virtual CPU devices each, one jax.distributed runtime — the full fedtpu
round program runs over the global 8-client mesh with its collectives
crossing the process boundary (TCP/gloo standing in for DCN). Asserts both
processes converge to the SAME global model, and that it matches the
single-process 8-device run bit-for-bit up to collective reassociation.

This is what the reference calls `mpirun --hostfile` (SURVEY.md §2c),
actually executed rather than just contract-checked.
"""

import os
import socket
import subprocess
import sys

import numpy as np

from tests import multihost_worker as mw


def _free_port() -> int:
    with socket.socket() as s:  # fedtpu: noqa[FTP009] bind-only port probe, never blocks on I/O
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_group(worker_name, trailing_args, timeout, nprocs=2,
                 local_devices=4):
    """Spawn ``nprocs`` worker processes (``local_devices`` virtual CPU
    devices each) on a fresh coordinator port and reap them; returns
    ``[(proc, output), ...]``. Process/device split is the knob: 2x4 and
    4x2 both form the same global 8-device mesh."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          worker_name)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["FEDTPU_TEST_LOCAL_DEVICES"] = str(local_devices)
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, worker, str(pid), str(nprocs), str(port),
         *trailing_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(nprocs)]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    except subprocess.TimeoutExpired:
        outs = ["<timeout>"] * nprocs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return list(zip(procs, outs))


def _launch_pair(worker_name, trailing_args, timeout, nprocs=2,
                 local_devices=4):
    """Run a worker group to successful completion. The free-port probe is
    inherently racy (the port is released before the coordinator binds it),
    so one retry with a fresh port absorbs a lost race instead of
    flaking."""
    last = None
    for _ in range(2):
        last = _spawn_group(worker_name, trailing_args, timeout,
                            nprocs=nprocs, local_devices=local_devices)
        if all(p.returncode == 0 for p, _ in last):
            return
    for p, out in last:
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"


def _launch_workers(tmp_path):
    _launch_pair("multihost_worker.py", [str(tmp_path)], timeout=240)


def test_two_process_round_matches_single_process(tmp_path):
    _launch_workers(tmp_path)

    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    # Both processes hold the identical averaged global model.
    np.testing.assert_allclose(p0, p1, atol=1e-6)

    accs = [float(open(tmp_path / f"acc_{pid}.txt").read())
            for pid in (0, 1)]
    assert accs[0] == accs[1]
    assert np.isfinite(accs[0])

    # The worker also ran (a) explicit ring/ppermute aggregation with its
    # hops crossing the process boundary (asserted == psum in-worker),
    # (b) a 2-D round on a transposed mesh whose MODEL-axis pairs span
    # both processes — true tp-over-DCN (asserted == the 1-D round
    # in-worker), (c) one int8-quantized exchange round whose gathered
    # payloads cross TCP (asserted within quantization error of exact),
    # and (d) a Byzantine-median round where the poisoned clients live on
    # process 0 and the order statistics span both processes (asserted to
    # hold the global where the mean breaks). Cross-process agreement of
    # the tp metrics:
    tp_accs = [float(open(tmp_path / f"tp_acc_{pid}.txt").read())
               for pid in (0, 1)]
    assert tp_accs[0] == tp_accs[1]
    assert np.isfinite(tp_accs[0])

    # Cross-check against the single-process 8-device run (the pytest
    # process's own virtual mesh), same constants imported from the worker
    # module so the two programs cannot drift: collective order may
    # reassociate floats, nothing more.
    import jax
    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh, client_sharding
    from fedtpu.parallel.round import build_round_fn, init_federated_state

    x, y = synthetic_income_like(mw.ROWS, mw.FEATURES, mw.CLASSES)
    packed = pack_clients(x, y, ShardConfig(num_clients=mw.NUM_CLIENTS,
                                            shuffle=False))
    mesh = make_mesh(num_clients=mw.NUM_CLIENTS)
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=mw.FEATURES,
                                                hidden_sizes=mw.HIDDEN))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(mw.SEED), mesh,
                                 mw.NUM_CLIENTS, init_fn, tx,
                                 same_init=True)
    step = build_round_fn(mesh, apply_fn, tx, mw.CLASSES,
                          rounds_per_step=mw.ROUNDS_PER_STEP)
    for _ in range(mw.OUTER_STEPS):
        state, _ = step(state, batch)
    single = np.asarray(jax.tree.leaves(state["params"])[0])[0]
    np.testing.assert_allclose(p0, single, atol=1e-5)


def _launch_loop_workers(tmp_path, mode="plain"):
    _launch_pair("multihost_loop_worker.py", [str(tmp_path), mode],
                 timeout=300)


def _run_loop_workers(tmp_path, mode="plain"):
    """Launch the 2-process loop-worker pair and return the per-process
    result dicts, asserting cross-process equality — the shared contract of
    every full-loop test."""
    import json

    _launch_loop_workers(tmp_path, mode=mode)
    runs = []
    for pid in (0, 1):
        with open(tmp_path / f"loop_{pid}.json") as f:
            runs.append(json.load(f))
    assert runs[0] == runs[1]
    return runs


def test_two_process_full_loop_matches_single_process(tmp_path):
    """The COMPLETE orchestration loop (run_experiment: history, held-out
    eval, early-stop machinery) across two jax.distributed processes — the
    reference's whole mpirun driver, not just the round kernel. Both
    processes must record identical histories, matching the single-process
    run."""
    from tests import multihost_loop_worker as mlw

    runs = _run_loop_workers(tmp_path)
    assert runs[0]["rounds_run"] == mlw.ROUNDS
    assert len(runs[0]["test_accuracy"]) == mlw.ROUNDS // mlw.EVAL_TEST_EVERY

    # Single-process reference run of the same config in this pytest
    # process (8 virtual devices, one process).
    from fedtpu.orchestration.loop import run_experiment

    single = run_experiment(mlw.experiment_config(), verbose=False)
    np.testing.assert_allclose(runs[0]["accuracy"],
                               single.global_metrics["accuracy"], atol=1e-5)
    np.testing.assert_allclose(runs[0]["test_accuracy"],
                               single.test_metrics["accuracy"], atol=1e-5)
    np.testing.assert_allclose(
        runs[0]["per_client_last"],
        np.asarray(single.per_client_metrics["accuracy"][-1]), atol=1e-5)


def test_two_process_pipelined_loop_with_checkpointing(tmp_path):
    """Pipelined-stop + periodic checkpointing across two processes. The
    orbax save is a COLLECTIVE — every process calls it (a process-0-only
    call deadlocks inside orbax's barrier; process-0 gating applies only to
    prints/JSONL), each persisting the client shards it owns. History must
    still match the single-process run, and a resume leg must continue from
    the distributed checkpoint."""
    from tests import multihost_loop_worker as mlw

    runs = _run_loop_workers(tmp_path, mode="pipelined_ckpt")
    assert runs[0]["rounds_run"] == mlw.ROUNDS

    # The collective saves landed on the shared dir (written jointly by
    # both processes, each persisting its own client shards): the first
    # leg's round-8 checkpoint plus the resume leg's round-12 one.
    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(str(tmp_path / "ck")) == mlw.RESUME_ROUNDS
    assert (tmp_path / "ck" / f"round_{mlw.ROUNDS:06d}").is_dir()

    # The worker's resume leg continued from the distributed checkpoint to
    # RESUME_ROUNDS on both processes with a consistent extended history.
    assert runs[0]["resume_rounds_run"] == mlw.RESUME_ROUNDS
    assert len(runs[0]["resume_accuracy"]) == mlw.RESUME_ROUNDS

    from fedtpu.orchestration.loop import run_experiment
    single = run_experiment(mlw.experiment_config(), verbose=False)
    np.testing.assert_allclose(runs[0]["accuracy"],
                               single.global_metrics["accuracy"], atol=1e-5)


def test_two_process_tensor_parallel_loop(tmp_path):
    """The 2-D dp x tp GSPMD engine across two processes: a (4, 2)
    ('clients','model') mesh spanning both, Megatron-sharded hidden
    weights, full loop. Histories must agree across processes and match
    the single-process 2-D run."""
    from tests import multihost_loop_worker as mlw

    runs = _run_loop_workers(tmp_path, mode="tp")
    assert runs[0]["rounds_run"] == mlw.ROUNDS

    from fedtpu.orchestration.loop import run_experiment
    single = run_experiment(mlw.experiment_config("tp"), verbose=False)
    np.testing.assert_allclose(runs[0]["accuracy"],
                               single.global_metrics["accuracy"], atol=1e-5)


def test_two_process_grid_search(tmp_path):
    """The reference's third driver — the federated hyperparameter grid
    (hyperparameters_tuning.py runs under mpirun) — across two processes:
    vmapped learning rates, uniform averaging, winner tracking with
    weights. Results must agree across processes and with the
    single-process sweep."""
    from tests import multihost_loop_worker as mlw

    runs = _run_loop_workers(tmp_path, mode="sweep")
    assert runs[0]["best_params"]["hidden_layer_sizes"]

    from fedtpu.sweep.grid import run_grid_search

    single = run_grid_search(mlw.experiment_config(),
                             hidden_grid=((8,), (4, 4)),
                             lr_grid=(0.01, 0.05), local_steps=10,
                             keep_weights=True, verbose=False)
    assert runs[0]["best_params"] == {
        "hidden_layer_sizes":
            list(single["params"]["hidden_layer_sizes"]),
        "learning_rate": single["params"]["learning_rate"]}
    np.testing.assert_allclose(runs[0]["best_accuracy"],
                               single["accuracy"], atol=1e-5)
    # The replicated winner-weights artifact must match the single-process
    # sweep too (keep_weights path across processes).
    np.testing.assert_allclose(
        runs[0]["weights_w0_sum"],
        float(np.asarray(single["weights"]["layers"][0]["w"]).sum()),
        atol=1e-4)
    assert len(runs[0]["table"]) == len(single["table"]) == 4
    for (hl, lr, acc), row in zip(runs[0]["table"], single["table"]):
        assert tuple(hl) == row["hidden_layer_sizes"]
        assert lr == row["learning_rate"]
        np.testing.assert_allclose(acc, row["accuracy"], atol=1e-5)


def test_four_process_round_kernel(tmp_path):
    """VERDICT r4 next #7: the kernel worker at FOUR processes with two
    virtual devices each — same global 8-device mesh, now with every
    collective crossing three process boundaries. All four processes must
    hold the identical global model, matching the 2-process run's
    contract (the worker's in-process assertions — ring==psum, tp-over-
    DCN, int8, Byzantine median — all execute at this split too)."""
    _launch_pair("multihost_worker.py", [str(tmp_path)], timeout=420,
                 nprocs=4, local_devices=2)
    params = [np.load(tmp_path / f"params_{pid}.npy") for pid in range(4)]
    for p in params[1:]:
        np.testing.assert_allclose(params[0], p, atol=1e-6)
    accs = [float(open(tmp_path / f"acc_{pid}.txt").read())
            for pid in range(4)]
    assert len(set(accs)) == 1 and np.isfinite(accs[0])
    tp_accs = [float(open(tmp_path / f"tp_acc_{pid}.txt").read())
               for pid in range(4)]
    assert len(set(tp_accs)) == 1 and np.isfinite(tp_accs[0])


def test_four_process_loop_with_checkpointing(tmp_path):
    """The full orchestration loop (pipelined stop + periodic collective
    checkpoints + resume leg) at 4 processes x 2 devices: all four
    histories identical, the distributed checkpoints complete on disk."""
    import json

    from tests import multihost_loop_worker as mlw

    _launch_pair("multihost_loop_worker.py",
                 [str(tmp_path), "pipelined_ckpt"], timeout=420,
                 nprocs=4, local_devices=2)
    runs = []
    for pid in range(4):
        with open(tmp_path / f"loop_{pid}.json") as f:
            runs.append(json.load(f))
    assert all(r == runs[0] for r in runs[1:])
    assert runs[0]["rounds_run"] == mlw.ROUNDS
    assert runs[0]["resume_rounds_run"] == mlw.RESUME_ROUNDS

    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(str(tmp_path / "ck")) == mlw.RESUME_ROUNDS


def test_process_death_terminates_survivors(tmp_path):
    """The reference's `comm.Abort` analogue (FL_CustomMLP...:203-205),
    executed: after one good round, process 1 dies abruptly (os._exit, no
    handshake). The survivor's next collective must NOT hang and must NOT
    keep computing a partial federation — the coordination service
    detects the missed heartbeats (shortened to 10 s in the worker) and
    TERMINATES the survivor with a fatal distributed-runtime diagnostic.
    Semantics documented in fedtpu.parallel.multihost.initialize."""
    results = _spawn_group("multihost_death_worker.py", [str(tmp_path)],
                           timeout=180)
    by_pid = {int(p.args[2]): (p, out) for p, out in results}
    dead, dead_out = by_pid[1]
    survivor, surv_out = by_pid[0]
    # Round 1 completed on both before the death.
    for pid in (0, 1):
        assert np.isfinite(float(
            open(tmp_path / f"death_round1_{pid}.txt").read()))
    assert dead.returncode == 77, dead_out[-2000:]
    # The survivor was terminated by the runtime: nonzero exit, within the
    # harness timeout (not hung), with the fatal-propagation diagnostic.
    assert survivor.returncode not in (0, 3), surv_out[-2000:]
    assert not (tmp_path / "survivor_never_died.txt").exists()
    assert ("distributed service detected fatal errors" in surv_out
            or "unhealthy" in surv_out
            or "DEADLINE_EXCEEDED" in surv_out
            or "UNAVAILABLE" in surv_out), surv_out[-3000:]
    # The survivor made essentially no post-death progress (its first
    # blocked fetch may or may not have landed a buffered round).
    prog = (tmp_path / "survivor_progress.txt")
    lines = prog.read_text().splitlines() if prog.exists() else []
    assert len(lines) <= 3, lines


def test_two_process_async_loop_matches_single_process(tmp_path):
    """The productized async FedBuff engine under jax.distributed: tick
    metrics, staleness, the K-buffer (M=6), collective checkpoints, and a
    resume leg — all across two processes, matching the single-process
    run exactly (arrival draws are deterministic in (seed, tick, client),
    so the trajectories must agree to collective-reassociation floats)."""
    from tests import multihost_loop_worker as mlw

    runs = _run_loop_workers(tmp_path, mode="async")
    assert runs[0]["rounds_run"] == mlw.ROUNDS
    assert runs[0]["staleness_max"] >= 1          # arrivals genuinely sparse
    assert runs[0]["resume_rounds_run"] == mlw.RESUME_ROUNDS

    from fedtpu.orchestration.loop import run_experiment
    single = run_experiment(mlw.experiment_config("async"), verbose=False)
    np.testing.assert_allclose(runs[0]["accuracy"],
                               single.global_metrics["accuracy"], atol=1e-5)
    np.testing.assert_allclose(
        runs[0]["staleness_mean"],
        float(np.mean([s.mean() for s in single.staleness])), atol=1e-6)
