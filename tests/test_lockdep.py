"""Lock-order sanitizer (fedtpu/analysis/lockdep.py): cycle detection,
drill determinism, the committed golden, and the check-gate fold.

The golden (tests/goldens/lockdep.json) pins the fleet's lock
discipline: two tracked locks, both leaf-level (zero nesting edges) —
deadlock-free by construction. Any new lock, nesting edge, or dropped
drill changes the canonical bytes and fails `fedtpu check --lockdep`.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from fedtpu.analysis.lockdep import (DRILLS, LockGraph, TrackedLock,
                                     compare_graph, default_golden_path,
                                     render_graph, run_drills)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "goldens", "lockdep.json")


# ------------------------------------------------------------ graph core
def test_tracked_lock_is_a_real_lock():
    g = LockGraph()
    lk = TrackedLock("l", g)
    assert lk.acquire()
    assert lk.locked()
    assert not lk.acquire(blocking=False)     # non-reentrant, like Lock
    lk.release()
    assert not lk.locked()
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_nested_acquisition_records_an_edge():
    g = LockGraph()
    a, b = TrackedLock("a", g), TrackedLock("b", g)
    with a:
        with b:
            pass
    assert g.edges == {("a", "b")}
    assert g.cycles() == []


def test_abba_ordering_is_detected_as_a_cycle():
    """The classic two-lock deadlock: A→B observed on one path, B→A on
    another. Scripted on one thread — the ORDER graph is what matters,
    not a live hang."""
    g = LockGraph()
    a, b = TrackedLock("a", g), TrackedLock("b", g)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert g.edges == {("a", "b"), ("b", "a")}
    assert g.cycles() == [["a", "b"]]


def test_three_lock_cycle_is_detected():
    g = LockGraph()
    locks = {n: TrackedLock(n, g) for n in "abc"}
    for first, second in (("a", "b"), ("b", "c"), ("c", "a")):
        with locks[first]:
            with locks[second]:
                pass
    assert g.cycles() == [["a", "b", "c"]]


def test_edges_recorded_per_thread_not_across_threads():
    """Holding A on thread 1 while thread 2 takes B is not a nesting
    edge — only the same thread's held stack orders acquisitions."""
    g = LockGraph()
    a, b = TrackedLock("a", g), TrackedLock("b", g)
    a_held = threading.Event()
    done = threading.Event()

    def other():
        a_held.wait(5.0)
        with b:
            pass
        done.set()

    t = threading.Thread(target=other, daemon=True)
    t.start()
    with a:
        a_held.set()
        done.wait(5.0)
    t.join(5.0)
    assert g.edges == set()


def test_failed_nonblocking_acquire_leaves_stack_clean():
    g = LockGraph()
    a = TrackedLock("a", g)
    assert a.acquire()
    assert not a.acquire(blocking=False)
    a.release()
    b = TrackedLock("b", g)
    with b:                        # nothing spuriously held from above
        pass
    assert g.edges == set()


# ---------------------------------------------------------------- drills
def test_drills_are_deterministic():
    first = render_graph(*run_drills())
    for _ in range(2):
        assert render_graph(*run_drills()) == first


def test_drills_match_committed_golden_bitwise():
    """Acceptance: the four pinned drills reproduce the committed golden
    byte for byte, and the discipline they pin is edge-free."""
    graph, ran = run_drills()
    assert [name for name, _ in DRILLS] == sorted(ran)
    cmp = compare_graph(render_graph(graph, ran), GOLDEN)
    assert cmp["ok"], cmp["reason"]
    assert graph.edges == set()          # every lock is leaf-level
    assert graph.cycles() == []
    assert {"netproxy._lock", "watchdog._lock"} == graph.nodes


def test_golden_covers_required_drills():
    payload = json.loads(open(GOLDEN, encoding="utf-8").read())
    assert payload["drills"] == ["netproxy_relay", "overlap_compile",
                                 "prefetch_writeback",
                                 "watchdog_arm_disarm"]
    assert payload["edges"] == [] and payload["cycles"] == []


def test_tampered_golden_fails_the_gate(tmp_path):
    graph, ran = run_drills()
    rendered = render_graph(graph, ran)
    bad = tmp_path / "lockdep.json"
    bad.write_text(rendered.replace('"edges":[]',
                                    '"edges":[["a","b"],["b","a"]]'))
    cmp = compare_graph(rendered, str(bad))
    assert not cmp["ok"]
    assert "diverges" in cmp["reason"]
    missing = compare_graph(rendered, str(tmp_path / "absent.json"))
    assert not missing["ok"] and "unreadable" in missing["reason"]


def test_default_golden_path_resolves_to_committed_file():
    assert os.path.abspath(default_golden_path()) == os.path.abspath(GOLDEN)
    assert os.path.exists(default_golden_path())


# ------------------------------------------------------------- check gate
@pytest.mark.slow
def test_check_lockdep_folds_into_exit_code(tmp_path):
    """`fedtpu check --lockdep` passes against the committed golden and
    fails against a tampered one. Subprocess: check pins the platform
    at import time."""
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--lockdep"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["lockdep"]["ok"] is True
    assert rep["lockdep"]["cycles"] == []

    bad = tmp_path / "bad.json"
    bad.write_text("{}\n")
    out = subprocess.run(
        [sys.executable, "-m", "fedtpu.cli", "check", "--json",
         "--lockdep", "--lockdep-golden", str(bad)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode != 0
    rep = json.loads(out.stdout)
    assert rep["lockdep"]["ok"] is False
