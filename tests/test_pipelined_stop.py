"""Pipelined-stop mode (RunConfig.pipelined_stop): the loop keeps one chunk
in flight and processes metrics one chunk late, removing a dispatch+fetch
RTT per chunk. Semantics contract (fedtpu/orchestration/loop.py):

* without early stop, histories and final params match the synchronous
  loop exactly (same chunks run, same order);
* with early stop, the RECORDED history matches the synchronous run (the
  in-flight overshoot chunk's metrics are dropped), while the final state
  may carry up to one extra chunk of training — the reference's own
  stop-signal lag (FL_CustomMLP...:132 vs :195);
* divergence still halts (state gate deferred to loop exit);
* checkpoint / held-out-eval boundaries still work (they sync inherently).
"""

import dataclasses

import jax
import numpy as np

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, RunConfig, ShardConfig)
from fedtpu.orchestration.loop import run_experiment


def _cfg(**run_kw):
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=256,
                        synthetic_features=6),
        shard=ShardConfig(num_clients=4, shuffle=False),
        model=ModelConfig(input_dim=6, hidden_sizes=(8,)),
        fed=FedConfig(rounds=12, tolerance=0.0),
        run=RunConfig(rounds_per_step=3, **run_kw),
    )


def test_pipelined_matches_sync_without_early_stop():
    sync = run_experiment(_cfg(), verbose=False)
    pipe = run_experiment(_cfg(pipelined_stop=True), verbose=False)
    assert pipe.rounds_run == sync.rounds_run == 12
    for k in sync.global_metrics:
        np.testing.assert_array_equal(sync.global_metrics[k],
                                      pipe.global_metrics[k])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        sync.final_params, pipe.final_params)


def test_pipelined_early_stop_history_matches_sync():
    # tolerance=1 makes every round "no significant change": both modes
    # must stop at round patience+1 with identical recorded histories.
    def cfg(pipelined):
        base = _cfg(pipelined_stop=pipelined)
        return dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, rounds=30,
                                          tolerance=1.0,
                                          termination_patience=4))
    sync = run_experiment(cfg(False), verbose=False)
    pipe = run_experiment(cfg(True), verbose=False)
    assert sync.stopped_early and pipe.stopped_early
    assert pipe.rounds_run == sync.rounds_run
    for k in sync.global_metrics:
        np.testing.assert_array_equal(sync.global_metrics[k],
                                      pipe.global_metrics[k])


def test_pipelined_divergence_still_halts(tmp_path):
    base = _cfg(pipelined_stop=True, checkpoint_dir=str(tmp_path / "ck"))
    cfg = dataclasses.replace(
        base,
        fed=dataclasses.replace(base.fed, rounds=20),
        # An absurd learning rate reliably drives the loss to NaN (the same
        # trigger test_aux_subsystems uses; 1e6 alone is survivable under
        # Adam's scale-invariant updates).
        optim=dataclasses.replace(base.optim, learning_rate=1e18))
    res = run_experiment(cfg, verbose=False)
    assert res.diverged
    assert res.rounds_run < 20
    # The quarantine label must match the SAVED state's round — in
    # pipelined mode up to one chunk past the divergent metrics round,
    # never behind it (review r2: honest label==state pairing).
    from fedtpu.orchestration.checkpoint import latest_step
    label = latest_step(str(tmp_path / "ck" / "diverged"))
    chunk = cfg.run.rounds_per_step
    assert label is not None
    assert res.rounds_run <= label <= res.rounds_run + 2 * chunk


def test_pipelined_with_checkpoint_and_test_eval(tmp_path):
    cfg = _cfg(pipelined_stop=True, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=6, eval_test_every=3)
    res = run_experiment(cfg, verbose=False)
    assert res.rounds_run == 12
    # One held-out eval entry per due round, like the sync loop.
    assert len(res.test_metrics["accuracy"]) == 4
    from fedtpu.orchestration.checkpoint import latest_step
    assert latest_step(str(tmp_path / "ck")) == 12
