"""The tier-1 lint gate: `python -m fedtpu.cli lint fedtpu/ tests/ bench.py`.

One in-process invocation of the real CLI entry point over the whole
repo, so a new lint finding (or an unjustified suppression regression)
fails the ordinary test suite without any extra CI infrastructure.
Marker-free by design — this rides in the default `-m 'not slow'` flow.

The linter is pure AST (no jax, no backend). The per-file rules cost
well under a second over the whole tree; the interprocedural pass
(FTP011/FTP012/FTP013 over the module call graphs) is budgeted below so
it can never silently blow tier-1 up.
"""

import os
import time

from fedtpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Whole-repo wall-time ceiling for one full lint pass (every rule,
# including the interprocedural concurrency/determinism pass). CI CPUs
# are slow; the pass takes ~2 s on a laptop — 30 s is the point where
# something superlinear has crept into the call-graph flow.
ANALYSIS_BUDGET_S = 30.0


def test_repo_lint_gate_is_clean(capsys):
    t0 = time.perf_counter()
    rc = cli_main(["lint",
                   os.path.join(REPO, "fedtpu"),
                   os.path.join(REPO, "tests"),
                   os.path.join(REPO, "bench.py")])
    elapsed = time.perf_counter() - t0
    out = capsys.readouterr().out
    assert rc == 0, f"fedtpu lint found regressions:\n{out}"
    # The gate really walked the tree (guards against a silently-empty
    # path list reporting a vacuous pass).
    assert "0 findings" in out
    files = int(out.rsplit(",", 1)[1].split()[0])
    assert files > 50, f"lint gate only saw {files} files"
    assert elapsed < ANALYSIS_BUDGET_S, (
        f"whole-repo analysis took {elapsed:.1f}s — the interprocedural "
        f"pass must stay under {ANALYSIS_BUDGET_S:.0f}s on CPU")


def test_concurrency_determinism_pass_gates_repo_wide(capsys):
    """The interprocedural rules alone, explicitly selected: the repo is
    clean under FTP011/FTP012/FTP013 (only justified noqa survive), and
    the selection really ran the checkers over the package."""
    rc = cli_main(["lint", "--select", "FTP011,FTP012,FTP013",
                   "--show-suppressed",
                   os.path.join(REPO, "fedtpu"),
                   os.path.join(REPO, "tests"),
                   os.path.join(REPO, "bench.py")])
    out = capsys.readouterr().out
    assert rc == 0, f"concurrency/determinism regressions:\n{out}"
    assert "0 findings" in out
    # The known justified suppression (cohort restore writes _state
    # before any prefetch is in flight) is visible — proof the pass
    # actually analyzed the threaded subsystems rather than no-opping.
    assert "scheduler.py" in out and "[suppressed]" in out


def test_suppressions_carry_justifications():
    """Every `# fedtpu: noqa[...]` in the repo must say WHY: bare
    suppressions (nothing after the closing bracket) are banned."""
    import re

    pat = re.compile(r"#\s*fedtpu:\s*noqa\[[A-Z0-9,\s]+\](.*)")
    offenders = []
    for base in ("fedtpu", "tests"):
        for dirpath, _, files in os.walk(os.path.join(REPO, base)):
            if "__pycache__" in dirpath:
                continue
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                for i, line in enumerate(open(path, encoding="utf-8"), 1):
                    m = pat.search(line)
                    if m and not m.group(1).strip():
                        offenders.append(f"{os.path.relpath(path, REPO)}:{i}")
    assert not offenders, (
        f"noqa without an inline justification: {offenders}")
