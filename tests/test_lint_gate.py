"""The tier-1 lint gate: `python -m fedtpu.cli lint fedtpu/ tests/ bench.py`.

One in-process invocation of the real CLI entry point over the whole
repo, so a new lint finding (or an unjustified suppression regression)
fails the ordinary test suite without any extra CI infrastructure.
Marker-free by design — this rides in the default `-m 'not slow'` flow.

The linter is pure AST (no jax, no backend), so this costs well under a
second even though it covers every .py file in the package and tests.
"""

import os

from fedtpu.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_lint_gate_is_clean(capsys):
    rc = cli_main(["lint",
                   os.path.join(REPO, "fedtpu"),
                   os.path.join(REPO, "tests"),
                   os.path.join(REPO, "bench.py")])
    out = capsys.readouterr().out
    assert rc == 0, f"fedtpu lint found regressions:\n{out}"
    # The gate really walked the tree (guards against a silently-empty
    # path list reporting a vacuous pass).
    assert "0 findings" in out
    files = int(out.rsplit(",", 1)[1].split()[0])
    assert files > 50, f"lint gate only saw {files} files"


def test_suppressions_carry_justifications():
    """Every `# fedtpu: noqa[...]` in the repo must say WHY: bare
    suppressions (nothing after the closing bracket) are banned."""
    import re

    pat = re.compile(r"#\s*fedtpu:\s*noqa\[[A-Z0-9,\s]+\](.*)")
    offenders = []
    for base in ("fedtpu", "tests"):
        for dirpath, _, files in os.walk(os.path.join(REPO, base)):
            if "__pycache__" in dirpath:
                continue
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                for i, line in enumerate(open(path, encoding="utf-8"), 1):
                    m = pat.search(line)
                    if m and not m.group(1).strip():
                        offenders.append(f"{os.path.relpath(path, REPO)}:{i}")
    assert not offenders, (
        f"noqa without an inline justification: {offenders}")
