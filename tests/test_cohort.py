"""fedtpu.cohort — sharded client-state store + streaming cohort scheduler
(ISSUE 7 tier-1 suite).

Pins the contracts docs/scaling.md documents:
- cohort-store mode is BITWISE-equal to the vmap path at full
  participation (the acceptance criterion) — history, losses, test
  cadence, and final params;
- the store round-trips records bitwise on both backends, and mmap vs
  memory backends produce identical training trajectories;
- mid-run checkpoint/restore resumes to the identical history and final
  params as an uninterrupted run (store rows ride the same orbax commit);
- the serving engine's store-backed eviction preserves per-user identity
  across evictions and across a checkpoint/restore split;
- sampling policies are deterministic pure functions of (seed, round),
  with identity order at full participation (what makes parity possible);
- peak host RSS is FLAT in total client count under a fixed cohort size
  (the memory-model claim; measured per-row in subprocesses).

The 1M-population bench row is `slow`-marked (full tier only).
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, OptimConfig, RunConfig, ShardConfig)
from fedtpu.cohort import ClientStateStore, CohortSampler
from fedtpu.cohort.store import state_template

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(num_clients=8, cohort_size=0, rounds=3, **kw):
    fed_kw = dict(rounds=rounds, cohort_size=cohort_size)
    run_kw = {}
    for k in ("client_store", "client_store_path", "cohort_sampling",
              "cohort_seed", "cohort_trace", "same_init", "weighting"):
        if k in kw:
            fed_kw[k] = kw.pop(k)
    for k in ("checkpoint_dir", "checkpoint_every", "eval_test_every",
              "rounds_per_step", "keep_checkpoints"):
        if k in kw:
            run_kw[k] = kw.pop(k)
    assert not kw, f"unknown keys {kw}"
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=num_clients),
        model=ModelConfig(hidden_sizes=(8,)),
        fed=FedConfig(**fed_kw),
        run=RunConfig(**run_kw),
    )


def _assert_trees_equal(a, b):
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------------------ sampler

def test_sampler_uniform_full_population_is_identity():
    """Full participation draws IDENTITY order — the ordering that makes
    the cohort reduction bitwise-comparable to the vmap path."""
    s = CohortSampler(8, 8)
    np.testing.assert_array_equal(s.sample(0)[0], np.arange(8))
    np.testing.assert_array_equal(s.sample(5)[0], np.arange(8))
    # Two disjoint half-cohorts also cover everyone, in identity order.
    two = CohortSampler(8, 4).sample(0, num_cohorts=2)
    np.testing.assert_array_equal(two.ravel(), np.arange(8))


def test_sampler_policies_deterministic_and_distinct():
    for policy, extra in (("uniform", {}),
                          ("weighted", {"weights": np.arange(1.0, 101.0)}),
                          ("trace", {"trace_users":
                                     np.arange(100)[::-1] % 100})):
        s1 = CohortSampler(100, 8, policy=policy, seed=3, **extra)
        s2 = CohortSampler(100, 8, policy=policy, seed=3, **extra)
        for r in (0, 1, 7):
            a, b = s1.sample(r, 2), s2.sample(r, 2)
            np.testing.assert_array_equal(a, b)          # pure in (seed, r)
            assert len(set(a.ravel().tolist())) == a.size  # chunk-disjoint
    # Rejection-sampling regime (need << total) stays distinct too.
    big = CohortSampler(100_000, 16, seed=1).sample(2, 2)
    assert len(set(big.ravel().tolist())) == big.size


def test_sampler_weighted_excludes_zero_weight_clients():
    w = np.ones(64)
    w[10:] = 0.0                     # only clients 0..9 are available
    s = CohortSampler(64, 8, policy="weighted", weights=w)
    for r in range(4):
        assert s.sample(r).max() < 10


def test_sampler_trace_walk_and_exhaustion():
    # Trace order drives cohort membership, wrapping circularly.
    tu = np.array([5, 5, 3, 3, 9, 1], np.int64)
    s = CohortSampler(10, 3, policy="trace", trace_users=tu)
    np.testing.assert_array_equal(s.sample(0)[0], [5, 3, 9])
    # Only 4 distinct users exist: a cohort of 5 must fail loudly.
    s5 = CohortSampler(10, 5, policy="trace", trace_users=tu)
    with pytest.raises(ValueError, match="distinct users"):
        s5.sample(0)


def test_sampler_guards():
    with pytest.raises(ValueError, match="cohort_size"):
        CohortSampler(4, 5)
    with pytest.raises(ValueError, match="weights"):
        CohortSampler(4, 2, policy="weighted")
    with pytest.raises(ValueError, match="nonnegative"):
        CohortSampler(4, 2, policy="weighted", weights=-np.ones(4))
    with pytest.raises(ValueError, match="outside the population"):
        CohortSampler(4, 2, policy="trace",
                      trace_users=np.array([0, 7], np.int64))
    with pytest.raises(ValueError, match="disjoint cohorts"):
        CohortSampler(8, 3).sample(0, num_cohorts=3)


# -------------------------------------------------------------------- store

def test_store_roundtrip_memory_and_mmap(tmp_path):
    template = [((3, 2), np.dtype(np.float32)), ((4,), np.dtype(np.int32))]
    rng = np.random.default_rng(0)
    ids = np.array([0, 7, 3], np.int64)
    leaves = [rng.normal(size=(3, 3, 2)).astype(np.float32),
              rng.integers(0, 9, size=(3, 4)).astype(np.int32)]
    keys = rng.integers(0, 2**32, size=(3, 2), dtype=np.uint32)
    for backend, path in (("memory", None),
                          ("mmap", str(tmp_path / "s.bin"))):
        st = ClientStateStore(template, 16, backend=backend, path=path)
        assert (st.versions(ids) == 0).all()
        st.write(ids, leaves, keys=keys)
        got = st.read(ids)
        for want, have in zip(leaves, got):
            np.testing.assert_array_equal(want, have)
        np.testing.assert_array_equal(st.read_keys(ids), keys)
        assert (st.versions(ids) == 1).all()
        assert (st.participation(ids) == 1).all()
        untouched = np.array([1, 2], np.int64)
        assert (st.versions(untouched) == 0).all()
        st.write(ids[:1], [l[:1] for l in leaves])   # version bumps per write
        assert st.versions(ids).tolist() == [2, 1, 1]
        # checkpoint_arrays carries ONLY touched rows; a fresh store
        # restored from it reads back bitwise.
        arrs = st.checkpoint_arrays()
        assert arrs["store_ids"].shape[0] == 3
        st2 = ClientStateStore(template, 16)
        st2.restore_arrays(arrs)
        for want, have in zip(st.read(ids), st2.read(ids)):
            np.testing.assert_array_equal(want, have)
        np.testing.assert_array_equal(st2.versions(ids), st.versions(ids))


def test_store_sharding_partitions_ids():
    template = [((2,), np.dtype(np.float32))]
    shards = [ClientStateStore(template, 10, shard_index=i, num_shards=3)
              for i in range(3)]
    ids = np.arange(10, dtype=np.int64)
    owned = np.stack([s.owns(ids) for s in shards])
    assert (owned.sum(axis=0) == 1).all()      # every id owned exactly once
    assert sum(s.rows for s in shards) == 10


def test_store_guards(tmp_path):
    template = [((2,), np.dtype(np.float32))]
    with pytest.raises(ValueError, match="backend"):
        ClientStateStore(template, 4, backend="redis")
    with pytest.raises(ValueError, match="path"):
        ClientStateStore(template, 4, backend="mmap")
    with pytest.raises(ValueError, match="total_clients"):
        ClientStateStore(template, 0)
    with pytest.raises(ValueError, match="shard_index"):
        ClientStateStore(template, 4, shard_index=2, num_shards=2)


# ------------------------------------------------- shard failover (ISSUE 12)

def _two_shards(total=11):
    """The gateway-fleet partition: two shards over one population."""
    template = [((3,), np.dtype(np.float32)), ((2,), np.dtype(np.int32))]
    return [ClientStateStore(template, total, shard_index=i, num_shards=2)
            for i in range(2)]


def test_two_shard_partition_is_disjoint_and_exhaustive():
    """The 2-process ownership contract the gateway fleet routes by:
    owns() masks are disjoint AND exhaustive over the population, both
    before and after a failover absorb flips shard 1's ids to shard 0."""
    s0, s1 = _two_shards()
    ids = np.arange(11, dtype=np.int64)
    assert not (s0.owns(ids) & s1.owns(ids)).any()      # disjoint
    assert (s0.owns(ids) | s1.owns(ids)).all()          # exhaustive
    assert s0.rows + s1.rows == 11
    # After the survivor absorbs the dead shard, its mask alone covers
    # the whole population — the fleet keeps answering for every id.
    s1.generation = "g"
    s0.absorb_shard(s1.checkpoint_arrays(), expected_generation="g")
    assert s0.owns(ids).all()


def test_shard_handoff_roundtrip_is_bitwise():
    """Flush-export from the dying shard, absorb into the survivor: the
    absorbed rows read back bitwise (records, versions, keys), and
    writes to adopted ids keep working through the overlay."""
    s0, s1 = _two_shards()
    rng = np.random.default_rng(3)
    ids = np.array([1, 5, 9], np.int64)                 # shard-1 ids
    leaves = [rng.normal(size=(3, 3)).astype(np.float32),
              rng.integers(0, 9, size=(3, 2)).astype(np.int32)]
    keys = rng.integers(0, 2**32, size=(3, 2), dtype=np.uint32)
    s1.write(ids, leaves, keys=keys)
    s1.generation = "launchA"
    assert s0.absorb_shard(s1.checkpoint_arrays(),
                           expected_generation="launchA") == 3
    for want, have in zip(s1.read(ids), s0.read(ids)):
        np.testing.assert_array_equal(want, have)
    np.testing.assert_array_equal(s0.versions(ids), s1.versions(ids))
    np.testing.assert_array_equal(s0.read_keys(ids), keys)
    # The survivor's own checkpoint now carries the adopted ids, so a
    # post-failover resume keeps answering for them (store_absorbed).
    arrs = s0.checkpoint_arrays()
    assert arrs["store_absorbed"].tolist() == [1]
    s2 = ClientStateStore(s0.template, s0.total_clients, shard_index=0,
                          num_shards=2)
    s2.restore_arrays(arrs)
    for want, have in zip(s0.read(ids), s2.read(ids)):
        np.testing.assert_array_equal(want, have)
    # Adopted ids stay writable (version bumps ride the overlay).
    s0.write(ids[:1], [l[:1] for l in leaves])
    assert s0.versions(ids).tolist()[0] == 2


def test_shard_export_digest_and_generation_fences():
    """Corrupt or stale exports are refused loudly: a tampered record
    fails the sha256 digest, a wrong generation fails the fence, and a
    wrong-shard id set is rejected."""
    s0, s1 = _two_shards()
    s1.write(np.array([1, 3], np.int64),
             [np.ones((2, 3), np.float32),
              np.ones((2, 2), np.int32)])
    s1.generation = "live"
    good = s1.checkpoint_arrays()

    tampered = dict(good)
    recs = good["store_records"].copy()
    recs[0, 0] ^= 0xFF
    tampered["store_records"] = recs
    with pytest.raises(ValueError, match="digest mismatch"):
        s0.absorb_shard(tampered, expected_generation="live")

    with pytest.raises(ValueError, match="stale handoff"):
        s0.absorb_shard(good, expected_generation="previous-life")

    own = dict(good)
    own["store_shard_index"] = np.int64(0)   # "absorb yourself"
    with pytest.raises(ValueError, match="cannot absorb"):
        s0.absorb_shard(own, expected_generation="live")


def test_restore_arrays_verifies_digest_and_shard_identity():
    """restore_arrays (the checkpoint path) applies the same fences: a
    truncated/overwritten restore fails the digest check and a
    checkpoint from another shard is refused."""
    s0, s1 = _two_shards()
    s1.write(np.array([1], np.int64),
             [np.full((1, 3), 2.0, np.float32),
              np.full((1, 2), 4, np.int32)])
    arrs = s1.checkpoint_arrays()

    fresh = ClientStateStore(s1.template, s1.total_clients, shard_index=1,
                             num_shards=2)
    corrupt = dict(arrs)
    recs = arrs["store_records"].copy()
    recs[0, -1] ^= 0xFF
    corrupt["store_records"] = recs
    with pytest.raises(ValueError, match="digest mismatch"):
        fresh.restore_arrays(corrupt)

    with pytest.raises(ValueError, match="belongs to shard"):
        s0.restore_arrays(arrs)          # shard-1 checkpoint into shard 0


# ------------------------------------------------------------------ parity

def test_cohort_full_participation_bitwise_equals_vmap():
    """THE acceptance parity: cohort_size == num_clients routes through
    the store + scan-over-cohorts machinery yet reproduces the vmap
    path's history, losses, test cadence, and final params bitwise."""
    from fedtpu.orchestration.loop import run_experiment
    ref = run_experiment(_cfg(rounds=3, eval_test_every=1), verbose=False)
    coh = run_experiment(_cfg(rounds=3, eval_test_every=1, cohort_size=8),
                         verbose=False)
    assert coh.rounds_run == ref.rounds_run == 3
    for k in ("accuracy", "precision", "recall", "f1"):
        assert coh.global_metrics[k] == ref.global_metrics[k]
        assert coh.pooled_metrics[k] == ref.pooled_metrics[k]
        assert coh.test_metrics[k] == ref.test_metrics[k]
        for a, b in zip(coh.per_client_metrics[k],
                        ref.per_client_metrics[k]):
            np.testing.assert_array_equal(np.sort(np.asarray(a)),
                                          np.sort(np.asarray(b)))
    for a, b in zip(coh.loss, ref.loss):
        np.testing.assert_array_equal(np.sort(np.asarray(a).ravel()),
                                      np.sort(np.asarray(b).ravel()))
    _assert_trees_equal(coh.final_params, ref.final_params)


def test_mmap_backend_bitwise_equals_memory(tmp_path):
    from fedtpu.orchestration.loop import run_experiment
    mem = run_experiment(_cfg(rounds=2, cohort_size=4), verbose=False)
    mm = run_experiment(
        _cfg(rounds=2, cohort_size=4, client_store="mmap",
             client_store_path=str(tmp_path / "store.bin")),
        verbose=False)
    for k in ("accuracy", "precision", "recall", "f1"):
        assert mm.global_metrics[k] == mem.global_metrics[k]
    _assert_trees_equal(mm.final_params, mem.final_params)


def test_cohort_checkpoint_resume_is_bitwise(tmp_path):
    """Interrupt after round 4, resume to 6: history and final params
    match the uninterrupted 6-round run exactly — the restored store
    rows, sampler round index, and global params all line up."""
    from fedtpu.orchestration.loop import run_experiment
    ref = run_experiment(
        _cfg(rounds=6, cohort_size=4,
             checkpoint_dir=str(tmp_path / "ref"), checkpoint_every=2),
        verbose=False)
    half = _cfg(rounds=4, cohort_size=4,
                checkpoint_dir=str(tmp_path / "split"), checkpoint_every=2)
    run_experiment(half, verbose=False)
    resumed = run_experiment(half.replace(fed=dataclasses.replace(half.fed, rounds=6)),
                             verbose=False, resume=True)
    assert resumed.rounds_run == 6
    for k in ("accuracy", "precision", "recall", "f1"):
        assert resumed.global_metrics[k] == ref.global_metrics[k]
    _assert_trees_equal(resumed.final_params, ref.final_params)


def test_cohort_config_guards(tmp_path):
    from fedtpu.orchestration.loop import run_experiment
    with pytest.raises(ValueError, match="cohort_size"):
        run_experiment(_cfg(num_clients=4, cohort_size=8), verbose=False)
    with pytest.raises(ValueError, match="async"):
        cfg = _cfg(cohort_size=4)
        run_experiment(cfg.replace(fed=dataclasses.replace(cfg.fed, async_mode=True)),
                       verbose=False)
    with pytest.raises(ValueError, match="robust"):
        cfg = _cfg(cohort_size=4)
        run_experiment(
            cfg.replace(fed=dataclasses.replace(cfg.fed,
                        robust_aggregation="median")),
            verbose=False)
    with pytest.raises(ValueError, match="path"):
        run_experiment(_cfg(cohort_size=4, client_store="mmap"),
                       verbose=False)
    with pytest.raises(ValueError, match="cohort-trace"):
        run_experiment(_cfg(cohort_size=4, cohort_sampling="trace"),
                       verbose=False)


# ----------------------------------------------------- serving integration

def test_engine_store_preserves_identity_across_eviction():
    """Store-backed eviction: a user bounced out of the C slots and later
    readmitted gets ITS OWN state back, bitwise — not whatever the slot
    accumulated in between."""
    from fedtpu.parallel.async_fed import read_client_slot
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.telemetry.metrics import MetricsRegistry
    from tests.test_serving import _small_cfg

    eng = ServingEngine(_small_cfg(cohort=2, tick_interval_s=0.0),
                        registry=MetricsRegistry())
    eng.attach_store(total_users=16)
    # Fill both slots, then snapshot user 0's trained slot state.
    for i, u in enumerate((0, 1)):
        eng.offer(0.1 * (i + 1), u, 0.0)
        eng.drain()
    slot0 = eng.binder.peek(0)
    assert slot0 is not None
    before = [np.asarray(v)
              for v in read_client_slot(eng.state, eng.C, slot0)]
    # End-to-end: users 2 and 3 evict users 0 and 1 at tick time; the
    # evictees' records hit the store.
    for i, u in enumerate((2, 3)):
        eng.offer(0.3 + 0.1 * i, u, 0.0)
        eng.drain()
    assert eng.binder.peek(0) is None
    assert eng.binder.evictions == 2
    assert len(eng.store._touched) >= 2
    # User 0's persisted record is its pre-eviction slot state, bitwise.
    rec = eng.store.read(np.asarray([0], np.int64))
    for a, b in zip(before, rec):
        np.testing.assert_array_equal(a, b[0])
    # Swap user 0 back in (the tick-time load path): the slot now holds
    # user 0's OWN record again, not what the interloper trained there.
    slot, evicted = eng.binder.bind(0)
    assert evicted in (2, 3)
    eng._swap_slot(slot, evicted_user=evicted, new_user=0)
    after = [np.asarray(v) for v in read_client_slot(eng.state, eng.C, slot)]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)


def test_engine_store_checkpoint_restore_is_bitwise(tmp_path):
    """The store's touched rows ride the engine's orbax commit: restore
    mid-stream (with evictions already persisted) and the remaining
    replay matches the uninterrupted run's history and params."""
    import jax

    from fedtpu.serving.engine import ServingEngine
    from fedtpu.telemetry.metrics import MetricsRegistry
    from tests.test_serving import _small_cfg, _small_trace

    cfg = _small_cfg(cohort=4)           # 500 trace users over 4 slots:
    _, t, user, lat = _small_trace(arrivals=80)   # evictions guaranteed
    half = 40

    ref = ServingEngine(cfg, registry=MetricsRegistry())
    ref.attach_store(total_users=500)
    ref.offer_many(zip(user.tolist(), t.tolist(), lat.tolist()))
    ref.drain()
    assert ref.binder.evictions > 0

    eng1 = ServingEngine(cfg, registry=MetricsRegistry())
    eng1.attach_store(total_users=500)
    eng1.offer_many(zip(user[:half].tolist(), t[:half].tolist(),
                        lat[:half].tolist()))
    eng1.checkpoint(str(tmp_path))

    eng2 = ServingEngine(cfg, registry=MetricsRegistry())
    eng2.attach_store(total_users=500)
    eng2.restore(str(tmp_path))
    s1, s2 = eng1.binder.state(), eng2.binder.state()
    np.testing.assert_array_equal(s2["users"], s1["users"])
    np.testing.assert_array_equal(s2["slots"], s1["slots"])
    assert int(s2["evictions"]) == int(s1["evictions"])
    eng2.offer_many(zip(user[half:].tolist(), t[half:].tolist(),
                        lat[half:].tolist()))
    eng2.drain()

    assert eng2.history_lines() == ref.history_lines()
    for a, b in zip(jax.tree.leaves(eng2.state["params"]),
                    jax.tree.leaves(ref.state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_slot_helpers_roundtrip():
    """read_client_slot/write_client_slot — the primitives the serving
    swap path is built on — round-trip one client's rows bitwise."""
    import jax

    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh
    from fedtpu.parallel.async_fed import (read_client_slot,
                                           write_client_slot)
    from fedtpu.parallel.round import init_federated_state

    init_fn, _ = build_model(ModelConfig(input_dim=4, num_classes=2,
                                         hidden_sizes=(4,)))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=4)
    state = init_federated_state(jax.random.key(0), mesh, 4, init_fn, tx)
    vals = [np.asarray(v) for v in read_client_slot(state, 4, 2)]
    bumped = [v + 1 if np.issubdtype(v.dtype, np.floating) else v
              for v in vals]
    state = write_client_slot(state, 4, 2, bumped)
    got = [np.asarray(v) for v in read_client_slot(state, 4, 2)]
    for a, b in zip(bumped, got):
        np.testing.assert_array_equal(a, b)
    # Other slots untouched.
    other = [np.asarray(v) for v in read_client_slot(state, 4, 1)]
    assert any(o.size for o in other)


def test_state_template_matches_slot_leaves():
    import jax

    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh
    from fedtpu.parallel.round import init_federated_state

    init_fn, _ = build_model(ModelConfig(input_dim=4, num_classes=2,
                                         hidden_sizes=(4,)))
    mesh = make_mesh(num_clients=4)
    state = init_federated_state(jax.random.key(0), mesh, 4, init_fn,
                                 build_optimizer(OptimConfig()))
    tpl = state_template(state, 4)
    assert len(tpl) >= 2           # params + optimizer moments at least
    for shape, dtype in tpl:
        assert isinstance(shape, tuple) and isinstance(dtype, np.dtype)
    # Template rows describe ONE client's record: no leading client axis.
    per_client = [tuple(np.asarray(l).shape[1:])
                  for l in jax.tree.leaves(state)
                  if hasattr(l, "shape") and l.ndim and l.shape[0] == 4]
    assert all(s in per_client for s, _ in tpl)


# ----------------------------------------------------------- memory model

def _scale_row(total, store, rounds=1, extra=()):
    cmd = [sys.executable, os.path.join(REPO, "benchmarks", "scaling.py"),
           "--scale-row", "--total-clients", str(total), "--store", store,
           "--cohort-size", "64", "--scale-rounds", str(rounds), *extra]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)     # real host device count, real RSS
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=560)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_peak_rss_flat_in_population():
    """The memory-model claim: 10x the simulated population under a fixed
    cohort size moves peak host RSS by store-header noise, not by model
    state (each row measured in its own subprocess => independent
    ru_maxrss high-water marks)."""
    small = _scale_row(10_000, "memory")
    big = _scale_row(100_000, "memory")
    assert big["store_apparent_bytes"] >= 10 * small["store_apparent_bytes"]
    delta = big["peak_rss_bytes"] - small["peak_rss_bytes"]
    # Observed ~1 MB on this box; 64 MB bounds allocator/page-cache noise
    # while still failing loudly if state materializes O(total_clients).
    assert delta < 64 * 2**20, (
        f"peak RSS grew {delta / 2**20:.1f} MiB for 10x the population "
        f"({small['peak_rss_bytes']} -> {big['peak_rss_bytes']})")


@pytest.mark.slow
def test_million_client_round_completes_flat(tmp_path):
    """The acceptance artifact, as a test: one full cohort round over a
    1M-simulated-client population (mmap store) completes on CPU with
    resident store bytes ~cohort-sized while the apparent store is GBs."""
    row = _scale_row(1_000_000, "mmap",
                     extra=("--store-path", str(tmp_path / "store.bin")))
    assert row["rounds"] >= 1
    assert row["store_apparent_bytes"] > 10**9          # ~1.7 GB apparent
    assert row["store_resident_bytes"] < 64 * 2**20     # cohort-sized
    assert row["peak_rss_bytes"] < 1536 * 2**20         # ~510 MB observed


# ------------------------------------------ config-validator rejections

# Every composition the cohort scan body does not reproduce must be
# rejected at startup by _validate_cohort_config with a message that
# names the offending knob — a silent wrong-math run is the failure
# mode these guard against. One row per rejection branch.
_REJECTIONS = [
    # (fed overrides, run overrides, message fragment naming the knob)
    (dict(cohort_size=16), {}, r"cohort_size=16 exceeds the population"),
    (dict(client_store="redis"), {}, r"client_store must be"),
    (dict(async_mode=True), {}, r"synchronous engine only"),
    ({}, dict(model_parallel=2), r"model_parallel=1"),
    (dict(participation_rate=0.5), {}, r"--participation-rate"),
    (dict(server_opt="adam"), {}, r"no server_opt / DP"),
    (dict(dp_clip_norm=1.0), {}, r"no server_opt / DP"),
    (dict(dp_clip_norm=1.0, dp_noise_multiplier=0.5), {},
     r"no server_opt / DP"),
    (dict(dp_clip_norm=1.0, dp_adaptive_clip=True), {},
     r"no server_opt / DP"),
    # Coordinate-wise robust rules are supported (uniform + psum only);
    # whole-update rules and synthetic byzantine injection stay rejected.
    (dict(robust_aggregation="trimmed_mean"), {}, r"unweighted"),
    (dict(robust_aggregation="median", weighting="uniform",
          aggregation="ring"), {}, r"psum backend"),
    (dict(robust_aggregation="krum"), {}, r"vmap engine"),
    (dict(byzantine_clients=2), {}, r"poisoned serving traces"),
    (dict(compress="8bit"), {}, r"compressed\s+exchange"),
    (dict(scaffold=True), {}, r"SCAFFOLD"),
    (dict(personalize_steps=3), {}, r"personalize_steps"),
    (dict(init_weights_npz="w.npz"), {}, r"init_weights_npz"),
    ({}, dict(on_divergence="rollback"), r"on_divergence='halt' only"),
    ({}, dict(fault_plan='{"faults": []}'), r"on_divergence='halt' only"),
    ({}, dict(pipelined_stop=True), r"pipelined_stop"),
    (dict(cohort_sampling="trace"), {}, r"--cohort-trace"),
]


@pytest.mark.parametrize("fed_kw,run_kw,match", _REJECTIONS,
                         ids=[f"{i}:{m[:24]}" for i, (_, _, m)
                              in enumerate(_REJECTIONS)])
def test_cohort_config_rejections(fed_kw, run_kw, match):
    from fedtpu.cohort.scheduler import _validate_cohort_config
    cfg = _cfg(num_clients=8, cohort_size=4)
    cfg = dataclasses.replace(
        cfg,
        fed=dataclasses.replace(cfg.fed, **fed_kw),
        run=dataclasses.replace(cfg.run, **run_kw))
    with pytest.raises(ValueError, match=match):
        _validate_cohort_config(cfg)


def test_cohort_config_valid_baseline_passes():
    """The base config every rejection row perturbs must itself pass —
    otherwise the rows above could be failing for the wrong reason."""
    from fedtpu.cohort.scheduler import _validate_cohort_config
    _validate_cohort_config(_cfg(num_clients=8, cohort_size=4))
