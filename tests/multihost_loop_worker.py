"""Worker for the multi-process FULL-LOOP test (tests/test_multihost_e2e.py).

Where tests/multihost_worker.py validates the raw round program across two
jax.distributed processes, this worker runs the COMPLETE orchestration loop
— run_experiment with history, early stopping, and held-out eval — the way
the reference runs its whole ``train_and_evaluate`` driver under ``mpirun
--hostfile``. Each process writes its recorded history; the parent test
asserts both processes and the single-process run agree.
"""

import json
import os
import sys

ROWS, FEATURES, CLASSES = 200, 6, 2
NUM_CLIENTS = 8
HIDDEN = (8,)
ROUNDS = 8
ROUNDS_PER_STEP = 2
EVAL_TEST_EVERY = 4
RESUME_ROUNDS = 12      # pipelined_ckpt mode: second leg resumes 8 -> 12


def experiment_config(mode: str = "plain", ckpt_dir=None):
    """``plain``: the default synchronous loop. ``pipelined_ckpt``: the
    pipelined-stop loop with periodic checkpointing — the interaction where
    the collective orbax save must line up across processes. ``tp``: the
    2-D GSPMD engine (model_parallel=2) on a ('clients','model') mesh that
    spans both processes. Coverage stated honestly: with devices laid out
    (dp=4, tp=2) each model-axis PAIR is intra-process — it is the
    clients-axis collectives (FedAvg psum, metric gathers) that cross the
    process boundary, exercising the full loop over a Megatron-sharded
    model, not tp-over-DCN itself."""
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               ModelConfig, RunConfig, ShardConfig)
    run_kw = {}
    fed_kw = {}
    if mode == "pipelined_ckpt":
        run_kw = {"pipelined_stop": True, "checkpoint_dir": ckpt_dir,
                  "checkpoint_every": 4}
    elif mode == "tp":
        run_kw = {"model_parallel": 2}
    elif mode == "async":
        # The productized async engine under jax.distributed: Bernoulli
        # arrivals, FedBuff K-buffer (M=6), staleness metrics — the
        # freshest-anchor gather, buffer carry, and arrival psum all
        # crossing the process boundary; checkpointing stays collective.
        fed_kw = {"async_mode": True, "weighting": "uniform",
                  "async_arrival_rate": 0.5, "async_buffer_size": 6}
        run_kw = {"checkpoint_dir": ckpt_dir, "checkpoint_every": 4}
    return ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=ROWS,
                        synthetic_features=FEATURES),
        shard=ShardConfig(num_clients=NUM_CLIENTS, shuffle=False),
        model=ModelConfig(input_dim=FEATURES, hidden_sizes=HIDDEN),
        fed=FedConfig(rounds=ROUNDS, tolerance=0.0, same_init=True,
                      **fed_kw),
        run=RunConfig(rounds_per_step=ROUNDS_PER_STEP,
                      eval_test_every=EVAL_TEST_EVERY, **run_kw),
    )


def main():
    pid, nprocs, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "plain"
    local = int(os.environ.get("FEDTPU_TEST_LOCAL_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={local}"
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from fedtpu.parallel import multihost

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs

    import numpy as np
    from fedtpu.orchestration.loop import run_experiment

    if mode == "sweep":
        # The reference's third driver (hyperparameters_tuning.py) under
        # multi-process: the vmapped-LR federated grid over the global
        # mesh. Every fetched array (pooled metrics, averaged winner
        # weights) is fully replicated, so the host reads work on every
        # process without extra plumbing.
        from fedtpu.sweep.grid import run_grid_search

        cfg = experiment_config()
        best = run_grid_search(cfg, hidden_grid=((8,), (4, 4)),
                               lr_grid=(0.01, 0.05), local_steps=10,
                               keep_weights=True, verbose=False)
        out = {
            "mode": mode,
            "best_params": {
                "hidden_layer_sizes":
                    list(best["params"]["hidden_layer_sizes"]),
                "learning_rate": best["params"]["learning_rate"]},
            "best_accuracy": best["accuracy"],
            "table": [[list(r["hidden_layer_sizes"]), r["learning_rate"],
                       r["accuracy"]] for r in best["table"]],
            "weights_w0_sum": float(
                np.asarray(best["weights"]["layers"][0]["w"]).sum()),
        }
        with open(os.path.join(outdir, f"loop_{pid}.json"), "w") as f:
            json.dump(out, f)
        print(f"sweep worker {pid}: ok best={out['best_params']}",  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol
              flush=True)
        return

    ckpt_dir = os.path.join(outdir, "ck")
    res = run_experiment(experiment_config(mode, ckpt_dir), verbose=True)

    out = {
        "mode": mode,
        "rounds_run": res.rounds_run,
        "accuracy": [float(v) for v in res.global_metrics["accuracy"]],
        "f1": [float(v) for v in res.global_metrics["f1"]],
        "test_accuracy": [float(v) for v in res.test_metrics["accuracy"]],
        "per_client_last": np.asarray(
            res.per_client_metrics["accuracy"][-1]).tolist(),
    }
    if mode == "async":
        out["staleness_mean"] = float(np.mean(
            [s.mean() for s in res.staleness]))
        out["staleness_max"] = float(max(s.max() for s in res.staleness))
    if mode in ("pipelined_ckpt", "async"):
        # Resume leg: a fresh run_experiment restores the DISTRIBUTED
        # checkpoint (written collectively above) on every process and
        # continues the round loop — the multi-process restore path (for
        # async, incl. anchors/pull_tick and the mid-run K-buffer).
        import dataclasses
        cfg2 = experiment_config(mode, ckpt_dir)
        cfg2 = dataclasses.replace(
            cfg2, fed=dataclasses.replace(cfg2.fed, rounds=RESUME_ROUNDS))
        res2 = run_experiment(cfg2, verbose=False, resume=True)
        out["resume_rounds_run"] = res2.rounds_run
        out["resume_accuracy"] = [float(v)
                                  for v in res2.global_metrics["accuracy"]]

    with open(os.path.join(outdir, f"loop_{pid}.json"), "w") as f:
        json.dump(out, f)
    print(f"loop worker {pid}: ok rounds={res.rounds_run}", flush=True)  # fedtpu: noqa[FTP005] stdout IS the worker->parent IPC protocol


if __name__ == "__main__":
    main()
