"""Partial client participation (FedAvg client sampling — fedtpu extension;
the reference trains every rank every round)."""

import numpy as np
import jax

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.utils.trees import clone
from fedtpu.parallel.round import build_round_fn, init_federated_state


def _setup(lr=0.004, **round_kw):
    x, y = synthetic_income_like(256, 6, 2)
    packed = pack_clients(x, y, ShardConfig(num_clients=8, shuffle=False))
    mesh = make_mesh(num_clients=8)
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6, hidden_sizes=(8,)))
    tx = build_optimizer(OptimConfig(learning_rate=lr))
    shard = client_sharding(mesh)
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    state = init_federated_state(jax.random.key(2), mesh, 8, init_fn, tx,
                                 same_init=False)
    step = build_round_fn(mesh, apply_fn, tx, 2, **round_kw)
    return state, batch, step, packed


def test_full_participation_is_default_behavior():
    state, batch, step_default, _ = _setup()
    state2 = clone(state)
    _, batch2, step_rate1, _ = _setup(participation_rate=1.0)
    a, _ = step_default(state, batch)
    b, _ = step_rate1(state2, batch)
    np.testing.assert_allclose(np.asarray(a["params"]["layers"][0]["w"]),
                               np.asarray(b["params"]["layers"][0]["w"]),
                               atol=0)


def test_sampling_is_deterministic_in_seed():
    state, batch, step, _ = _setup(participation_rate=0.5, participation_seed=7)
    state2 = clone(state)
    a, _ = step(state, batch)
    b, _ = step(state2, batch)
    np.testing.assert_allclose(np.asarray(a["params"]["layers"][0]["w"]),
                               np.asarray(b["params"]["layers"][0]["w"]),
                               atol=0)


def test_nonparticipants_keep_optimizer_moments():
    # With rate 0.0 nobody trains: params and moments must be unchanged.
    state, batch, step, _ = _setup(participation_rate=1e-9)
    before_w = np.asarray(state["params"]["layers"][0]["w"])
    before_mu = np.asarray(jax.tree.leaves(state["opt_state"])[1])
    new_state, _ = step(state, batch)
    np.testing.assert_allclose(
        np.asarray(new_state["params"]["layers"][0]["w"]), before_w, atol=0)
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(new_state["opt_state"])[1]), before_mu,
        atol=0)


def test_sampled_average_over_participants_only():
    # lr=0 makes the train step a parameter no-op (Adam moments still move for
    # participants, which is how we recover the sampled subset), so the new
    # global params must equal the data-size-weighted average over the
    # PARTICIPANTS' initial params ONLY — non-participants' params must not
    # leak into the average.
    state, batch, step, packed = _setup(lr=0.0, participation_rate=0.5,
                                        participation_seed=3)
    before = np.asarray(state["params"]["layers"][0]["w"])  # (C, in, out)
    mu_before = np.asarray(jax.tree.leaves(state["opt_state"])[1])
    new_state, _ = step(state, batch)
    after = np.asarray(new_state["params"]["layers"][0]["w"])
    mu_after = np.asarray(jax.tree.leaves(new_state["opt_state"])[1])

    part = np.array([not np.allclose(mu_before[c], mu_after[c])
                     for c in range(8)])
    assert 0 < part.sum() < 8  # actually sampled a strict subset

    w = packed.counts.astype(np.float64) * part
    expected = (before * (w / w.sum())[:, None, None]).sum(axis=0)
    for c in range(8):
        np.testing.assert_allclose(after[c], expected, atol=1e-6)


def test_different_rounds_sample_different_subsets():
    state, batch, step, _ = _setup(participation_rate=0.5, participation_seed=3,
                                   rounds_per_step=4)
    mu_before = np.asarray(jax.tree.leaves(state["opt_state"])[1])
    new_state, metrics = step(state, batch)
    # Across 4 rounds with rate .5, at least 5 of 8 clients should have
    # trained at least once (P[all 4 misses] = 1/16 per client).
    mu_after = np.asarray(jax.tree.leaves(new_state["opt_state"])[1])
    moved = sum(not np.allclose(mu_before[c], mu_after[c]) for c in range(8))
    assert moved >= 5
