"""Why the 2-D engine exists: per-device memory scaling (VERDICT r3 #3).

The reference replicates every model whole — one full copy per MPI rank
(FL_CustomMLPCLassifierImplementation_Multiple_Rounds.py:42) — so its
largest trainable model is whatever one process's memory holds. fedtpu's
1-D engine inherits that per-device shape: each client slot carries full
params + full Adam moments. The 2-D ('clients','model') engine
(fedtpu.parallel.tp) shards the hidden weights over the model axis; this
script produces the NUMBERS that justify it:

1. MEASURED per-device live state bytes on the virtual 8-device mesh for
   a fixed 2-client federation as tp grows 1 -> 2 -> 4 (1-D engine = the
   tp=1 baseline, on 2 devices). Bytes come from the actual device
   buffers (``addressable_shards``), not a model: params + Adam moments
   per device drop ~1/tp, and the tp=4 round genuinely executes at a
   size where the 1-D engine needs >4x the per-device state.
2. XLA compiled-program memory analysis (argument/output/temp/peak) of
   each round program — the compiler's own per-device accounting,
   including scratch.
3. EXACT accounting (jax.eval_shape — no allocation) of both layouts at
   v5e scale: the hidden=[32k,32k,32k] MLP whose per-device
   params+moments (24.4 GiB) cannot fit a 16-GiB v5e chip under the 1-D
   engine, while tp=2 (12.2 GiB) fits and tp=4 (6.1 GiB) fits with room
   for activations. Same math the ARCHITECTURE doc quotes.

The scaling law being demonstrated: per-device state bytes ~=
(C/dp) * (P_sharded/tp + P_replicated) * 12 B, where 12 B = fp32 param
+ Adam m + v. Only the logits head and the row-Linear biases are
replicated over 'model' (fedtpu/parallel/tp.py:mlp_tp_specs), so
P_replicated is tiny for wide MLPs and the drop tracks 1/tp closely.

Run: ``python benchmarks/tp_memory.py`` (~1 min, CPU — forces the
virtual 8-device mesh; tp>1 needs more devices than the 1-chip box).
"""

from __future__ import annotations

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()

import jax.numpy as jnp
import numpy as np

from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import synthetic_income_like
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import client_sharding, make_mesh, tp
from fedtpu.parallel.round import build_round_fn, init_federated_state
from fedtpu.utils.trees import max_device_bytes

NUM_CLIENTS = 2          # fixed federation; chips-per-client is the axis
V5E_HBM_GIB = 16.0       # v5e: 16 GiB HBM per chip
GIB = 1024.0 ** 3


def state_bytes(state) -> int:
    """Max-over-devices of measured params+opt_state bytes (the round
    counter and any server state ride along; they are scalars here)."""
    return max_device_bytes({"params": state["params"],
                             "opt": state["opt_state"]})


# ---------------------------------------------------------------- measured
def measured_scaling(hidden=(8192, 8192), input_dim=1024, rows=256):
    """Build the same 2-client federation on the 1-D engine and on the 2-D
    engine at tp in {2, 4}; measure per-device state bytes and the
    compiler's memory stats; run one real round on each."""
    x, y = synthetic_income_like(rows, input_dim, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=NUM_CLIENTS,
                                            shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=input_dim,
                                                hidden_sizes=hidden))
    tx = build_optimizer(OptimConfig())
    key = jax.random.key(0)
    batch_np = {"x": packed.x, "y": packed.y, "mask": packed.mask}
    rows_out = []

    def run(label, mesh, state, step, batch):
        compiled = step.lower(state, batch).compile()
        ma = compiled.memory_analysis()
        # Execute through the AOT executable (a jit call would compile the
        # same program a second time — the AOT compile shares no cache).
        state2, metrics = compiled(state, batch)   # really execute one round
        jax.block_until_ready(state2["params"])
        rows_out.append({
            "engine": label,
            "devices": int(np.prod(mesh.devices.shape)),
            "state_bytes_per_device": state_bytes(state2),
            "xla_argument_bytes": int(ma.argument_size_in_bytes),
            "xla_temp_bytes": int(ma.temp_size_in_bytes),
            "xla_peak_bytes": int(ma.peak_memory_in_bytes),
        })
        return state2

    # 1-D engine: 2 devices, one client's FULL model each — the reference's
    # replication shape (FL_CustomMLP...:42) on fedtpu's fast path.
    mesh1 = make_mesh(num_devices=NUM_CLIENTS, num_clients=NUM_CLIENTS)
    s1 = init_federated_state(key, mesh1, NUM_CLIENTS, init_fn, tx)
    b1 = {k: jax.device_put(v, client_sharding(mesh1))
          for k, v in batch_np.items()}
    run("1d", mesh1,  s1,
        build_round_fn(mesh1, apply_fn, tx, 2), b1)

    for mp in (2, 4):
        mesh2 = tp.make_mesh_2d(mp, NUM_CLIENTS)
        s2 = tp.init_federated_state_2d(key, mesh2, NUM_CLIENTS, init_fn, tx)
        b2 = {k: jax.device_put(v, tp.batch_sharding_2d(mesh2))
              for k, v in batch_np.items()}
        run(f"2d tp={mp}", mesh2, s2,
            tp.build_round_fn_2d(mesh2, apply_fn, tx, 2), b2)
    return rows_out


# ------------------------------------------------------- exact accounting
def exact_per_device_bytes(input_dim, hidden, num_classes, mp, dp=1,
                           clients_per_slot=1):
    """Per-device params+opt bytes for the 2-D layout, via eval_shape (no
    allocation): each leaf's bytes divided by the product of mesh-axis
    extents its PartitionSpec names. mp=1 == the 1-D engine's layout."""
    init_fn, _ = build_model(ModelConfig(input_dim=input_dim,
                                         hidden_sizes=hidden,
                                         num_classes=num_classes))
    tx = build_optimizer(OptimConfig())
    keys = jax.ShapeDtypeStruct((dp * clients_per_slot, 2), jnp.uint32)
    params = jax.eval_shape(jax.vmap(lambda k: init_fn(
        jax.random.wrap_key_data(k))), keys)
    opt = jax.eval_shape(jax.vmap(tx.init), params)
    specs = tp.tp_specs(params)
    extent = {"clients": dp, "model": mp}

    def leaf_bytes(leaf, spec):
        denom = 1
        for axis in spec:
            if axis is not None:
                denom *= extent[axis]
        return int(np.prod(leaf.shape)) * leaf.dtype.itemsize / denom

    pb = sum(jax.tree.leaves(jax.tree.map(leaf_bytes, params, specs)))
    # Adam: m and v mirror the param layout (sharding propagation); counts
    # are scalars. Charge every non-scalar opt leaf at the param ratio.
    ob = 2 * pb
    scalars = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                  for l in jax.tree.leaves(opt) if l.ndim <= 1)
    return pb + ob + scalars


def v5e_table(input_dim=1024, hidden=(32768, 32768, 32768), num_classes=16):
    rows = []
    for mp in (1, 2, 4, 8):
        b = exact_per_device_bytes(input_dim, hidden, num_classes, mp)
        rows.append({"tp": mp, "per_device_gib": b / GIB,
                     "fits_v5e": b / GIB < V5E_HBM_GIB})
    return rows


def main():
    print(f"== measured on the virtual 8-device mesh "
          f"(C={NUM_CLIENTS} clients, hidden=[8192,8192] fp32) ==")
    meas = measured_scaling()
    base = meas[0]["state_bytes_per_device"]
    for r in meas:
        r["vs_1d"] = round(base / r["state_bytes_per_device"], 2)
        print(json.dumps(r))
    # The guarantees the RESULTS table quotes: tp=2 halves, tp=4 quarters
    # (within 10% — the replicated logits head and row-biases are the slack).
    assert meas[1]["vs_1d"] > 1.8 and meas[2]["vs_1d"] > 3.6, meas
    assert meas[2]["xla_peak_bytes"] < meas[0]["xla_peak_bytes"] / 2, meas

    print(f"\n== exact accounting at v5e scale (hidden=[32768]*3, fp32, "
          f"Adam; {V5E_HBM_GIB:.0f} GiB HBM/chip) ==")
    tab = v5e_table()
    for r in tab:
        print(json.dumps(r))
    assert not tab[0]["fits_v5e"] and tab[1]["fits_v5e"], tab
    print("\n1-D engine (full replication, the reference's layout) cannot "
          "fit this model on a v5e chip; tp=2 fits, tp=4 leaves >9 GiB "
          "for activations.")


if __name__ == "__main__":
    main()
