"""Async (FedBuff-style) engine measured on the chip — VERDICT r4 next #1.

Two questions, answered with the repo's mandatory timing harness
(fedtpu.utils.timing: fetch-forced windows + flops-floor guard):

1. **Tick cost vs the sync round** at income-8 shapes: the async tick does
   the same local step plus anchor bookkeeping, arrival draws, and the
   freshest-anchor gather — what does that machinery cost next to the
   synchronous uniform delta round it degenerates to at arrival_rate=1?

2. **Accuracy vs arrival rate** on the standing non-IID preset
   (income-32-noniid): 300 server ticks at arrivals {1.0, 0.5, 0.25} x
   staleness_power {0, 0.5}, against the 300-round synchronous FedAvg
   answer. At arrival q, a tick trains ~q*C clients, so 300 ticks do ~q x
   the local work of 300 sync rounds — the table reports accuracy at equal
   TICKS (the wall-clock-fair comparison: a tick is a server cadence slot)
   plus mean/max staleness.

Usage: python benchmarks/async_bench.py [--json OUT.jsonl]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def bench_tick_cost():
    import jax

    from fedtpu.config import (DataConfig, ModelConfig, OptimConfig,
                               ShardConfig)
    from fedtpu.data import load_dataset
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.ops.server_opt import identity_server_optimizer
    from fedtpu.parallel import async_fed, client_sharding, make_mesh
    from fedtpu.parallel.round import build_round_fn, init_federated_state
    from fedtpu.utils.timing import (assert_above_flops_floor,
                                     compile_with_flops,
                                     measured_peak_flops, timed_rounds)

    C, RPS = 8, 100
    ds = load_dataset(DataConfig())
    mesh = make_mesh(num_clients=C)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train, ShardConfig(num_clients=C))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    peak = measured_peak_flops(dtype="float32",
                               device=mesh.devices.ravel()[0])

    rows = []

    def time_step(label, make_state, make_step):
        state = make_state()
        step, flops = compile_with_flops(make_step(), state, batch)
        samples = []
        for _ in range(3):
            sec, state, metrics = timed_rounds(step, state, batch, 10, RPS,
                                               peak, flops, label=label)
            samples.append(sec)
        sec = float(np.median(samples))
        assert_above_flops_floor(sec, flops, peak, label=label)
        rows.append({"row": "tick_cost", "label": label, "sec": sec,
                     "sec_range": [float(min(samples)),
                                   float(max(samples))],
                     "flops": flops})
        print(f"[async_bench] {label}: {sec:.3e} s/tick "
              f"(band [{min(samples):.3e}, {max(samples):.3e}])",
              file=sys.stderr)

    server = identity_server_optimizer()
    time_step(
        "sync uniform delta round (rps=100)",
        lambda: init_federated_state(jax.random.key(0), mesh, C, init_fn,
                                     tx, server_opt=server),
        lambda: build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                               weighting="uniform", server_opt=server,
                               rounds_per_step=RPS))
    for rate in (1.0, 0.5):
        time_step(
            f"async tick (arrival={rate}, tps=100)",
            lambda: async_fed.init_async_state(jax.random.key(0), mesh, C,
                                               init_fn, tx),
            lambda rate=rate: async_fed.build_async_round_fn(
                mesh, apply_fn, tx, ds.num_classes, arrival_rate=rate,
                ticks_per_step=RPS))
    return rows


def bench_trace_driven(trace_path):
    """Tick cost with arrivals sourced from a serving trace file — the
    driven-step twin of the synthetic-rate rows, so trace-driven and
    Bernoulli numbers sit side by side in one artifact.

    The trace's virtual timestamps are bucketed onto a (tps, C) 0/1 mask
    (tick index from the horizon, slot = user % C — the serving engine's
    bounded-cohort fold) and the compiled driven step replays that mask;
    same timing harness, same flops floor."""
    import jax
    import jax.numpy as jnp

    from fedtpu.config import DataConfig, ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data import load_dataset
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import async_fed, client_sharding, make_mesh
    from fedtpu.serving.traces import load_trace_arrays
    from fedtpu.utils.timing import (assert_above_flops_floor,
                                     compile_with_flops,
                                     measured_peak_flops, timed_rounds)

    C, TPS = 8, 100
    header, t, user, _lat = load_trace_arrays(trace_path)
    span = max(float(header.horizon_s),
               float(t[-1]) if len(t) else 1.0)
    tick = np.minimum((t / span * TPS).astype(np.int64), TPS - 1)
    masks = np.zeros((TPS, C), np.float32)
    masks[tick, user.astype(np.int64) % C] = 1.0
    density = float(masks.mean())

    ds = load_dataset(DataConfig())
    mesh = make_mesh(num_clients=C)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train, ShardConfig(num_clients=C))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    peak = measured_peak_flops(dtype="float32",
                               device=mesh.devices.ravel()[0])

    state = async_fed.init_async_state(jax.random.key(0), mesh, C,
                                       init_fn, tx)
    step = async_fed.build_async_round_fn(mesh, apply_fn, tx,
                                          ds.num_classes,
                                          ticks_per_step=TPS, driven=True)
    arrivals = jnp.asarray(masks)
    compiled, flops = compile_with_flops(step, state, batch, arrivals)

    label = (f"trace-driven tick (tps={TPS}, {header.arrivals} arrivals, "
             f"slot density {density:.2f})")
    samples = []
    for _ in range(3):
        sec, state, _ = timed_rounds(
            lambda s, b: compiled(s, b, arrivals), state, batch, 10, TPS,
            peak, flops, label=label)
        samples.append(sec)
    sec = float(np.median(samples))
    assert_above_flops_floor(sec, flops, peak, label=label)
    print(f"[async_bench] {label}: {sec:.3e} s/tick "
          f"(band [{min(samples):.3e}, {max(samples):.3e}])",
          file=sys.stderr)
    return [{"row": "tick_cost", "label": label, "sec": sec,
             "sec_range": [float(min(samples)), float(max(samples))],
             "flops": flops,
             "trace": {"path": trace_path, "users": header.users,
                       "arrivals": header.arrivals,
                       "slot_density": density}}]


def bench_accuracy_vs_arrival():
    from fedtpu.config import RunConfig, get_preset
    from fedtpu.orchestration.loop import run_experiment

    TICKS = 300
    base = get_preset("income-32-noniid")
    base = dataclasses.replace(
        base,
        fed=dataclasses.replace(base.fed, rounds=TICKS,
                                weighting="uniform",
                                termination_patience=10 ** 9),
        run=RunConfig(rounds_per_step=50, log_every=10 ** 9,
                      eval_test_every=TICKS))
    rows = []

    def run(label, **fed_kw):
        cfg = dataclasses.replace(
            base, fed=dataclasses.replace(base.fed, **fed_kw))
        t0 = time.perf_counter()
        res = run_experiment(cfg, verbose=False)
        wall = time.perf_counter() - t0
        row = {"row": "accuracy_vs_arrival", "label": label,
               "ticks": res.rounds_run,
               "client_mean_accuracy": res.global_metrics["accuracy"][-1],
               "pooled_accuracy": res.pooled_metrics["accuracy"][-1],
               "test_accuracy": res.test_metrics["accuracy"][-1],
               "wall_s": wall}
        if res.staleness:
            row["mean_staleness"] = float(
                np.mean([s.mean() for s in res.staleness]))
            row["max_staleness"] = float(
                max(s.max() for s in res.staleness))
        rows.append(row)
        print(f"[async_bench] {label}: client-mean "
              f"{row['client_mean_accuracy']:.4f}, pooled "
              f"{row['pooled_accuracy']:.4f}, test "
              f"{row['test_accuracy']:.4f}"
              + (f", staleness mean {row['mean_staleness']:.2f} max "
                 f"{row['max_staleness']:.0f}" if "mean_staleness" in row
                 else "")
              + f"  ({wall:.1f}s)", file=sys.stderr)

    run("sync FedAvg 300 rounds (uniform)")
    for rate in (1.0, 0.5, 0.25):
        for p in ((0.5,) if rate == 1.0 else (0.5, 0.0)):
            run(f"async arrival={rate} p={p}", async_mode=True,
                async_arrival_rate=rate, async_staleness_power=p)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--trace", default=None,
                    help="serving trace file (fedtpu.serving.traces "
                         "JSONL); adds a trace-driven tick_cost row "
                         "comparable to the synthetic-rate rows")
    args = ap.parse_args()
    rows = bench_tick_cost()
    if args.trace:
        rows += bench_trace_driven(args.trace)
    rows += bench_accuracy_vs_arrival()
    out = open(args.json, "w") if args.json else None
    for r in rows:
        line = json.dumps(r, default=float)
        print(line)
        if out:
            out.write(line + "\n")
    if out:
        out.close()


if __name__ == "__main__":
    main()
