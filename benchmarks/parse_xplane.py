"""Per-op aggregation of a jax.profiler xplane capture.

Usage: ``python benchmarks/parse_xplane.py <trace>/plugins/profile/*/\
*.xplane.pb`` — prints, per TPU device plane, the total duration and
event count of every HLO op, most expensive first. This is how the
round-4 roofline attribution (benchmarks/RESULTS.md 'Roofline') located
the activation-stream fusions that dominate the income round.
"""
import sys, collections
from tensorflow.tsl.profiler.protobuf import xplane_pb2
for path in sys.argv[1:]:
  print(f"=== file: {path}")
  xs = xplane_pb2.XSpace()
  xs.ParseFromString(open(path, "rb").read())
  for plane in xs.planes:
    print("== plane:", plane.name)
    if "TPU" not in plane.name and "device" not in plane.name.lower():
        continue
    ev_meta = {i: m.name for i, m in plane.event_metadata.items()}
    agg = collections.Counter()
    cnt = collections.Counter()
    for line in plane.lines:
        for ev in line.events:
            name = ev_meta.get(ev.metadata_id, str(ev.metadata_id))
            agg[name] += ev.duration_ps
            cnt[name] += 1
    total = sum(agg.values())
    print(f"  line events total {total/1e12*1e6:.1f} us (all lines)")
    for name, ps in agg.most_common(25):
        print(f"  {ps/1e6:10.1f} us  n={cnt[name]:<7} {name[:90]}")
