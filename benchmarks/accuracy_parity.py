"""Accuracy parity: fedtpu vs the reference-equivalent torch/MPI simulation.

The north star (BASELINE.md) is "matches the MPI baseline's test accuracy at
>=10x wallclock". bench.py measures the wallclock half; this script measures
the accuracy half: both systems train 8-client weighted FedAvg on the income
CSV (the reference's main-driver config, FL_CustomMLP...:211-252 retargeted
to the shipped dataset) and evaluate the post-averaging GLOBAL model on the
held-out 20% test split each eval period. The reference broadcasts this test
split and never uses it (FL_CustomMLP...:243-246); held-out eval is the
apples-to-apples comparison ground both systems share.

Prints one JSON line per system plus a verdict line:
    {"system": "reference-sim", "final_test_acc": ..., "best_test_acc": ...,
     "rounds_to": {"0.75": r, "0.80": r, "0.82": r}}
    {"system": "fedtpu", ...}
    {"parity": {"abs_diff_final": ..., "pass": true}}

Usage: python benchmarks/accuracy_parity.py [--rounds 300] [--eval-every 10]
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                           ModelConfig, RunConfig, ShardConfig,
                           default_income_csv)
from fedtpu.data.tabular import load_tabular_dataset

NUM_CLIENTS = 8
THRESHOLDS = (0.75, 0.80, 0.82)


def _summarize(accs: list, eval_every: int) -> dict:
    if not len(accs):
        raise SystemExit("no eval points recorded: --rounds must be >= "
                         "--eval-every")
    accs = np.asarray(accs, np.float64)
    rounds_to = {}
    for t in THRESHOLDS:
        hit = np.nonzero(accs >= t)[0]
        rounds_to[f"{t:.2f}"] = int((hit[0] + 1) * eval_every) if len(hit) else None
    return {"final_test_acc": round(float(accs[-1]), 4),
            "best_test_acc": round(float(accs.max()), 4),
            "rounds_to": rounds_to}


def run_reference_sim(ds, rounds: int, eval_every: int) -> dict:
    """The reference's per-round work (FL_CustomMLP...:63-120) in torch, plus
    held-out eval of the averaged global model every ``eval_every`` rounds."""
    import torch
    import torch.nn as nn

    torch.manual_seed(42)
    model_of = lambda: nn.Sequential(
        nn.Linear(ds.input_dim, 50), nn.ReLU(),
        nn.Linear(50, 200), nn.ReLU(),
        nn.Linear(200, ds.num_classes))

    n = len(ds.x_train)
    chunk = max(1, n // NUM_CLIENTS)
    shards = []
    for r in range(NUM_CLIENTS):
        s, e = r * chunk, (r + 1) * chunk if r != NUM_CLIENTS - 1 else n
        shards.append((torch.tensor(ds.x_train[s:e]),
                       torch.tensor(ds.y_train[s:e], dtype=torch.long)))

    models = [model_of() for _ in range(NUM_CLIENTS)]
    # Same-init across clients; run_fedtpu sets same_init=True and
    # shuffle=False to match, so both systems train from one init on
    # identically-composed contiguous shards and the residual delta is
    # attributable to framework differences, not setup mismatch.
    w0 = models[0].state_dict()
    for m in models[1:]:
        m.load_state_dict(w0)
    opts = [torch.optim.Adam(m.parameters(), lr=0.004) for m in models]
    scheds = [torch.optim.lr_scheduler.StepLR(o, step_size=30, gamma=0.5)
              for o in opts]
    crit = nn.CrossEntropyLoss()
    x_test = torch.tensor(ds.x_test)
    y_test = np.asarray(ds.y_test)

    accs = []
    for rnd in range(rounds):
        for m, o, sch, (x, y) in zip(models, opts, scheds, shards):
            o.zero_grad()
            crit(m(x), y).backward()
            o.step()
            sch.step()
        sizes = [len(x) for x, _ in shards]
        total = float(sum(sizes))
        with torch.no_grad():
            avg = {k: sum(m.state_dict()[k] * (s / total)
                          for m, s in zip(models, sizes))
                   for k in w0}
            for m in models:
                m.load_state_dict(avg)
            if (rnd + 1) % eval_every == 0:
                pred = models[0](x_test).argmax(dim=1).numpy()
                accs.append(float((pred == y_test).mean()))
    return _summarize(accs, eval_every)


def run_fedtpu(ds, rounds: int, eval_every: int) -> dict:
    from fedtpu.orchestration.loop import run_experiment

    cfg = ExperimentConfig(
        data=DataConfig(csv_path=default_income_csv()),
        shard=ShardConfig(num_clients=NUM_CLIENTS, shuffle=False),
        model=ModelConfig(input_dim=ds.input_dim, num_classes=ds.num_classes),
        fed=FedConfig(rounds=rounds, termination_patience=10 ** 9,
                      same_init=True),
        run=RunConfig(rounds_per_step=eval_every, eval_test_every=eval_every),
    )
    res = run_experiment(cfg, dataset=ds, verbose=False)
    return _summarize(res.test_metrics["accuracy"], eval_every)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=300)
    ap.add_argument("--eval-every", type=int, default=10)
    ap.add_argument("--skip-reference", action="store_true")
    args = ap.parse_args()

    ds = load_tabular_dataset(DataConfig(csv_path=default_income_csv()))

    ours = run_fedtpu(ds, args.rounds, args.eval_every)
    print(json.dumps({"system": "fedtpu", **ours}), flush=True)

    if not args.skip_reference:
        base = run_reference_sim(ds, args.rounds, args.eval_every)
        print(json.dumps({"system": "reference-sim", **base}), flush=True)
        diff = abs(ours["final_test_acc"] - base["final_test_acc"])
        print(json.dumps({"parity": {"abs_diff_final": round(diff, 4),
                                     "pass": bool(diff <= 0.01)}}))


if __name__ == "__main__":
    main()
