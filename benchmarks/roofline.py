"""Roofline + attribution for the income round program (VERDICT r3 #1).

Answers, with measurements on the real chip, WHY the headline round's
marginal MFU sits near 22% and what bound it actually saturates:

1. XLA cost/memory analysis of the compiled round: FLOPs, bytes
   accessed, and XLA's own ``optimal_seconds`` roofline estimate.
2. Marginal sec/round of the round and of its stages (train-only,
   train+aggregation, full) via the scan-length SLOPE method — two scan
   depths far apart, (t2 - t1) / (R2 - R1), which cancels the fixed
   dispatch+fetch cost exactly (fedtpu.utils.timing methodology).
3. Measured streaming ceilings for the round's activation-sized tensors
   (f32 and bf16 elementwise passes over the exact shapes).
4. MFU of the SAME round program at MXU-sized shapes (hidden 512/1024),
   demonstrating the framework clears 40% MFU whenever the workload's
   arithmetic intensity allows it.

Conclusion this script reproduces (benchmarks/RESULTS.md 'Roofline'):
the income round is BYTE-throughput bound on its (8, 1000, {50,200})
activation streams, which XLA already moves as bf16/u8; its 22%
marginal MFU is that bandwidth roofline, not scheduling headroom — the
program beats XLA's own HBM-model estimate ~3x via VMEM residency and
runs within ~1.2x of the measured elementwise streaming time of its
tensors, while the identical round at hidden 512 reaches >50% MFU.

Run: ``python benchmarks/roofline.py`` (~3 min on the v5e).
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import (DataConfig, ModelConfig, OptimConfig, ShardConfig,
                           default_income_csv)
from fedtpu.data import load_dataset
from fedtpu.data.sharding import pack_clients
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import client_sharding, make_mesh
from fedtpu.parallel.round import build_round_fn, init_federated_state
from fedtpu.training.client import make_local_train_step
from fedtpu.utils.timing import (compile_with_flops, force_fetch,
                                 marginal_slope, measured_peak_flops)
from fedtpu.utils.trees import clone

NUM_CLIENTS = 8




def income_setup():
    ds = load_dataset(DataConfig(csv_path=default_income_csv()))
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {"x": jax.device_put(packed.x, shard),
             "y": jax.device_put(packed.y, shard),
             "mask": jax.device_put(packed.mask, shard)}
    init_fn, apply_fn = build_model(
        ModelConfig(input_dim=ds.input_dim, num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                 init_fn, tx)
    return ds, mesh, shard, packed, batch, init_fn, apply_fn, tx, state


def main():
    (ds, mesh, shard, packed, batch,
     init_fn, apply_fn, tx, state) = income_setup()
    dev = mesh.devices.ravel()[0]
    peak = measured_peak_flops(device=dev)
    out = {"peak_flops": peak, "backend": dev.platform}

    # ---- 1. compiled-program analysis
    step1 = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                           rounds_per_step=1)
    compiled = step1.lower(clone(state), batch).compile()
    ca = compiled.cost_analysis()
    flops = float(ca["flops"])
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    optimal_s = float(ca.get("optimal_seconds", 0.0))
    out["flops_per_round"] = flops
    out["bytes_accessed"] = bytes_accessed
    out["xla_optimal_seconds"] = optimal_s

    # ---- 2. marginal attribution
    def full(R):
        step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                              rounds_per_step=R)
        return lambda: step(clone(state), batch)[1]["client_mean"]["accuracy"]

    local_train = make_local_train_step(apply_fn, tx)
    xd, yd, md = (jnp.asarray(packed.x), jnp.asarray(packed.y),
                  jnp.asarray(packed.mask))

    def train_only(R):
        @jax.jit
        def f(params, opt_state):
            def body(c, _):
                p, o = c
                p2, o2, loss = jax.vmap(local_train)(p, o, xd, yd, md)
                return (p2, o2), loss
            (p, o), losses = jax.lax.scan(body, (params, opt_state),
                                          length=R)
            return losses[-1].sum() + jax.tree.leaves(p)[0].sum()
        p0, o0 = clone(state["params"]), clone(state["opt_state"])
        return lambda: f(p0, o0)

    def train_agg(R):
        w = md.sum(axis=1)

        @jax.jit
        def f(params, opt_state):
            def body(c, _):
                p, o = c
                p2, o2, loss = jax.vmap(local_train)(p, o, xd, yd, md)
                g = jax.tree.map(
                    lambda t: (w.reshape((NUM_CLIENTS,) + (1,) * (t.ndim - 1))
                               * t).sum(0) / w.sum(), p2)
                p3 = jax.tree.map(
                    lambda gl, t: jnp.broadcast_to(gl[None], t.shape), g, p2)
                return (p3, o2), loss
            (p, o), losses = jax.lax.scan(body, (params, opt_state),
                                          length=R)
            return losses[-1].sum() + jax.tree.leaves(p)[0].sum()
        p0, o0 = clone(state["params"]), clone(state["opt_state"])
        return lambda: f(p0, o0)

    # Stage slopes carry ~1-2 us of window jitter each (the differences
    # below inherit it doubled); more reps narrow the min-window noise.
    m_full = marginal_slope(full, reps=6)
    m_train = marginal_slope(train_only, reps=6)
    m_agg = marginal_slope(train_agg, reps=6)
    out["marginal_s"] = {"full_round": m_full, "train_only": m_train,
                         "train_plus_agg": m_agg,
                         "eval_metrics": m_full - m_agg,
                         "aggregation": m_agg - m_train}
    out["marginal_mfu"] = flops / (m_full * peak)
    out["flops_floor_s"] = flops / peak

    # ---- 3. streaming ceilings on the round's activation shapes
    ceilings = {}
    for dt, name in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
        x = jnp.asarray(np.random.default_rng(0)
                        .standard_normal((8, 1000, 200)), dt)

        def gen(R, x=x, dt=dt):
            @jax.jit
            def f(x0):
                def body(c, _):
                    return (c * jnp.asarray(0.9999, dt)
                            + jnp.asarray(1e-4, dt),
                            c.astype(jnp.float32).sum())
                c, ss = jax.lax.scan(body, x0, length=R)
                return ss[-1]
            return lambda: f(x)
        m = marginal_slope(gen)
        nbytes = 2 * x.dtype.itemsize * x.size
        ceilings[name] = {"s_per_pass": m, "tb_per_s": nbytes / m / 1e12}
    out["stream_ceiling_8x1000x200"] = ceilings

    # ---- 4. same round program at MXU-sized shapes
    shapes = []
    for rows, hidden, lens in ((1000, (512, 512), (200, 800)),
                               (8000, (512, 512), (50, 200))):
        ds2 = load_dataset(DataConfig(csv_path=None,
                                      synthetic_rows=rows * NUM_CLIENTS,
                                      synthetic_features=14))
        packed2 = pack_clients(ds2.x_train, ds2.y_train,
                               ShardConfig(num_clients=NUM_CLIENTS))
        batch2 = {"x": jax.device_put(packed2.x, shard),
                  "y": jax.device_put(packed2.y, shard),
                  "mask": jax.device_put(packed2.mask, shard)}
        init2, apply2 = build_model(
            ModelConfig(input_dim=ds2.input_dim, hidden_sizes=hidden,
                        num_classes=ds2.num_classes))
        state2 = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                      init2, tx)

        def gen(R, apply2=apply2, state2=state2, batch2=batch2, ds2=ds2):
            step = build_round_fn(mesh, apply2, tx, ds2.num_classes,
                                  rounds_per_step=R)
            return lambda: step(clone(state2),
                                batch2)[1]["client_mean"]["accuracy"]
        s1 = build_round_fn(mesh, apply2, tx, ds2.num_classes,
                            rounds_per_step=1)
        _, fl2 = compile_with_flops(s1, clone(state2), batch2)
        m2 = marginal_slope(gen, lens)
        shapes.append({"rows_per_client": int(packed2.x.shape[1]),
                       "hidden": list(hidden), "marginal_s": m2,
                       "flops": fl2, "mfu": fl2 / (m2 * peak)})
    out["mxu_sized_rounds"] = shapes

    print(json.dumps(out, indent=2, default=float))
    head = out["marginal_s"]
    print(f"\n[roofline] income round marginal {m_full*1e6:.1f} us "
          f"(train {head['train_only']*1e6:.1f}, eval+metrics "
          f"{head['eval_metrics']*1e6:.1f}, agg "
          f"{head['aggregation']*1e6:.1f}); flops floor "
          f"{out['flops_floor_s']*1e6:.1f} us -> marginal MFU "
          f"{100*out['marginal_mfu']:.1f}%")
    print(f"[roofline] XLA bytes accessed {bytes_accessed/1e6:.1f} MB/round; "
          f"XLA HBM-model optimal {optimal_s*1e6:.1f} us "
          f"(we run {optimal_s/m_full:.1f}x faster: VMEM residency + bf16 "
          "streams)")
    for s in shapes:
        print(f"[roofline] hidden {s['hidden']} rows/client "
              f"{s['rows_per_client']}: {100*s['mfu']:.1f}% MFU — the same "
              "round program clears 40% when shapes are MXU-sized")


if __name__ == "__main__":
    main()
