"""End-to-end sweep wall clock: fedtpu's 90-config grid vs the measured
reference-equivalent sklearn sweep (VERDICT r3 #2).

fedtpu side — ``run_grid_search`` on the income data, three ways:
fixed-400 bucketed (production default), fixed-400 unbucketed (the
round-3 one-compile-per-architecture path), plateau-stop bucketed (the
sklearn-faithful semantics). Wall clock includes EVERY compile; a
second bucketed run in the same process shows the warm-cache time.
Completion is fetch-forced implicitly: run_grid_search materializes
every metric to numpy before returning.

Reference side — a faithful single-host simulation of
``hyperparameters_tuning.py:80-132`` under ``mpirun -np 8``: per config
every rank fits a fresh ``MLPClassifier(hidden, learning_rate_init=lr,
max_iter=400, random_state=42)`` on its shard (sklearn's own tol-1e-4 /
10-epoch plateau stopping active, exactly what the reference runs),
local predictions BEFORE averaging, rank-0 uniform weight average, and
pooled metrics from the concatenated predictions. Ranks run
concurrently under mpirun, so fit+predict time is credited
/min(8, cpu_count) (ideal oversubscription; 1 on this box — the
speedup shrinks accordingly on real 8-core hosts and both numbers are
in the artifact); the averaging + metrics path stays serial.

Run: ``python benchmarks/sweep_bench.py [--skip-sklearn]`` (~15 min:
~2 min fedtpu + ~10 min sklearn baseline on the 1-core box).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import warnings

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np

from fedtpu.config import DataConfig, ExperimentConfig, ShardConfig, \
    default_income_csv
from fedtpu.data import load_dataset
from fedtpu.sweep.grid import HIDDEN_GRID, LR_GRID, run_grid_search

NUM_CLIENTS = 8


def bench_fedtpu(cfg, ds):
    out = {}
    for label, kw in (
            # r5 default: arch axis stacked into the vmap — 2 launches.
            ("fixed400_bucketed", dict(bucket_pad=True)),
            # r4 behavior: one launch per architecture (10 launches).
            ("fixed400_bucketed_per_arch", dict(bucket_pad=True,
                                                vmap_arch=False)),
            ("fixed400_unbucketed", dict(bucket_pad=False)),
            ("plateau_bucketed", dict(bucket_pad=True, plateau_stop=True)),
    ):
        t0 = time.perf_counter()
        best = run_grid_search(cfg, dataset=ds, verbose=False, **kw)
        dt = time.perf_counter() - t0
        out[label] = {"wall_s": dt, "compile_count": best["compile_count"],
                      "launch_count": best["launch_count"],
                      "best": best["params"],
                      "best_accuracy": best["accuracy"],
                      "tie_set_size": len(best["tie_set"]),
                      "configs": len(best["table"])}
        print(f"[sweep] fedtpu {label}: {dt:.1f} s, "
              f"{best['compile_count']} compiles / "
              f"{best['launch_count']} launches, winner {best['params']} "
              f"acc {best['accuracy']:.4f}, tie set "
              f"{len(best['tie_set'])}", flush=True)
    # Warm-cache rerun of the production mode: the steady-state sweep time
    # once the jit cache holds the two depth-class programs.
    t0 = time.perf_counter()
    best = run_grid_search(cfg, dataset=ds, verbose=False, bucket_pad=True)
    out["fixed400_bucketed_warm"] = {"wall_s": time.perf_counter() - t0,
                                     "best": best["params"]}
    print(f"[sweep] fedtpu fixed400_bucketed warm rerun: "
          f"{out['fixed400_bucketed_warm']['wall_s']:.1f} s", flush=True)
    return out


def bench_sklearn(ds):
    from sklearn.neural_network import MLPClassifier
    from sklearn.metrics import (accuracy_score, precision_score,
                                 recall_score, f1_score)

    n = len(ds.x_train)
    chunk = n // NUM_CLIENTS
    shards = []
    for r in range(NUM_CLIENTS):
        s, e = r * chunk, (r + 1) * chunk if r != NUM_CLIENTS - 1 else n
        shards.append((ds.x_train[s:e], ds.y_train[s:e]))

    parallel = min(NUM_CLIENTS, os.cpu_count() or 1)
    t_fit = 0.0          # concurrent under mpirun: credited /parallel
    t_serial = 0.0       # rank-0 averaging + pooled metrics: serial
    best_acc, best_cfg = -1.0, None
    for hidden in HIDDEN_GRID:
        for lr in LR_GRID:
            coefs, inters, preds_all, y_all = [], [], [], []
            for x_s, y_s in shards:
                t0 = time.perf_counter()
                clf = MLPClassifier(hidden_layer_sizes=hidden,
                                    learning_rate_init=lr, max_iter=400,
                                    random_state=42)
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    clf.fit(x_s, y_s)
                preds = clf.predict(x_s)
                t_fit += time.perf_counter() - t0
                coefs.append(clf.coefs_)
                inters.append(clf.intercepts_)
                preds_all.append(preds)
                y_all.append(y_s)
            t0 = time.perf_counter()
            # rank-0 uniform average (hyperparameters_tuning.py:24-46).
            avg_c = [np.mean([c[i] for c in coefs], axis=0)
                     for i in range(len(coefs[0]))]
            avg_i = [np.mean([c[i] for c in inters], axis=0)
                     for i in range(len(inters[0]))]
            del avg_c, avg_i
            yp = np.concatenate(preds_all)
            yt = np.concatenate(y_all)
            acc = accuracy_score(yt, yp)
            precision_score(yt, yp, average="weighted", zero_division=0)
            recall_score(yt, yp, average="weighted", zero_division=0)
            f1_score(yt, yp, average="weighted", zero_division=0)
            t_serial += time.perf_counter() - t0
            if acc > best_acc:
                best_acc, best_cfg = acc, (tuple(hidden), lr)
        print(f"[sweep] sklearn arch {hidden} done "
              f"(fit so far {t_fit:.0f} s)", flush=True)
    return {"fit_s": t_fit, "serial_s": t_serial,
            "assumed_parallelism": parallel,
            "wall_s": t_fit / parallel + t_serial,
            "wall_s_if_8cores": t_fit / NUM_CLIENTS + t_serial,
            "best": {"hidden_layer_sizes": best_cfg[0],
                     "learning_rate": best_cfg[1]},
            "best_accuracy": best_acc}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-sklearn", action="store_true")
    args = ap.parse_args()
    cfg = ExperimentConfig(
        data=DataConfig(csv_path=default_income_csv(),
                        label_column="income"),
        shard=ShardConfig(num_clients=NUM_CLIENTS))
    ds = load_dataset(cfg.data)
    result = {"fedtpu": bench_fedtpu(cfg, ds)}
    if not args.skip_sklearn:
        result["sklearn_reference"] = bench_sklearn(ds)
        ours = result["fedtpu"]["plateau_bucketed"]["wall_s"]
        ref = result["sklearn_reference"]["wall_s"]
        result["speedup_plateau_vs_reference"] = ref / ours
        result["speedup_if_8core_host"] = (
            result["sklearn_reference"]["wall_s_if_8cores"] / ours)
        print(f"[sweep] sklearn reference sweep: {ref:.1f} s "
              f"(fit {result['sklearn_reference']['fit_s']:.1f} s / "
              f"parallel {result['sklearn_reference']['assumed_parallelism']}"
              f" + serial {result['sklearn_reference']['serial_s']:.1f} s)"
              f" -> fedtpu plateau sweep {ours:.1f} s = "
              f"{ref / ours:.1f}x (8-core counterfactual "
              f"{result['speedup_if_8core_host']:.1f}x)", flush=True)
    print(json.dumps(result, default=float))


if __name__ == "__main__":
    main()
