"""Overhead of the resilience layer on the happy path, plus restore cost.

The resilience machinery must be free when nothing fails: the per-round
additions are one ``chunk_limit`` arithmetic call (fault-plan runs
only), one heartbeat rewrite (when ``--heartbeat`` is set), and the
pre/post-round injector hooks. This benchmark pins numbers on each:

    chunk_limit:   ns per call against an armed multi-fault plan;
    heartbeat:     ms per atomic write+rename (the per-round liveness
                   cost a supervised run pays);
    faulted run:   wall-clock of a short synthetic run with a straggler
                   plan whose delay is 0-cost (delay_s ~ 0) vs the same
                   run with no plan — the injection bookkeeping delta;
    rollback:      time from divergence detection to restored state
                   (checkpoint restore + replay bookkeeping), measured
                   as the extra wall-clock of a NaN+rollback run over
                   the unfaulted run, minus the replayed rounds' own
                   compute.

Run: ``python benchmarks/resilience_bench.py`` (~60 s on the CPU box).
Emits bench.py-style output: detail lines on stderr, one full JSON blob
last on stdout (and to --out).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def _run(rounds, **run_kw):
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    cfg = ExperimentConfig(
        data=DataConfig(csv_path=None, synthetic_rows=512),
        shard=ShardConfig(num_clients=8),
        fed=FedConfig(rounds=rounds),
        run=RunConfig(**run_kw),
    )
    t0 = time.perf_counter()
    res = run_experiment(cfg, verbose=False)
    return time.perf_counter() - t0, res


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--reps", type=int, default=3,
                    help="wall-clock reps; best-of is reported")
    ap.add_argument("--out", default="BENCH_RESILIENCE.json")
    args = ap.parse_args(argv)

    from fedtpu.resilience.faults import FaultInjector, FaultPlan
    from fedtpu.resilience.supervisor import write_heartbeat

    result = {"rounds": args.rounds}

    # --- chunk_limit: the only per-chunk cost every fault-plan run pays.
    plan = FaultPlan.load(
        {"seed": 0, "faults": [
            {"kind": "straggler", "round": r, "clients": [0],
             "delay_s": 0.001} for r in (20, 40, 60, 80)]},
        num_clients=8, rounds=100)
    inj = FaultInjector(plan)
    n = 200_000
    t0 = time.perf_counter()
    for i in range(n):
        inj.chunk_limit(i % 100, 8)
    result["chunk_limit_ns"] = (time.perf_counter() - t0) / n * 1e9
    print(f"chunk_limit: {result['chunk_limit_ns']:.0f} ns/call",
          file=sys.stderr)

    # --- heartbeat: one atomic write+rename per round.
    with tempfile.TemporaryDirectory() as td:
        hb = os.path.join(td, "hb.json")
        n = 2000
        t0 = time.perf_counter()
        for i in range(n):
            write_heartbeat(hb, status="running", round=i, restarts=0)
        result["heartbeat_ms"] = (time.perf_counter() - t0) / n * 1e3
    print(f"heartbeat: {result['heartbeat_ms']:.3f} ms/write",
          file=sys.stderr)

    # --- happy-path bookkeeping: plan armed but (near-)free faults.
    near_free = json.dumps({"seed": 0, "faults": [
        {"kind": "straggler", "round": r, "clients": [0], "delay_s": 1e-4}
        for r in range(2, args.rounds, 3)]})
    base_s = faulted_s = float("inf")
    for _ in range(args.reps):
        base_s = min(base_s, _run(args.rounds)[0])
        faulted_s = min(faulted_s, _run(args.rounds,
                                        fault_plan=near_free)[0])
    result["baseline_s"] = base_s
    result["faulted_s"] = faulted_s
    result["injection_overhead_s"] = faulted_s - base_s
    print(f"run {args.rounds} rounds: baseline {base_s:.3f} s, "
          f"with armed plan {faulted_s:.3f} s "
          f"(delta {faulted_s - base_s:+.3f} s)", file=sys.stderr)

    # --- rollback restore: divergence -> restored -> caught back up.
    nan_round = args.rounds // 2 + 1
    nan_plan = json.dumps({"seed": 0, "faults": [
        {"kind": "nan_update", "round": nan_round, "clients": [1]}]})
    rb_s = float("inf")
    with tempfile.TemporaryDirectory() as td:
        for rep in range(args.reps):
            ck = os.path.join(td, f"ck{rep}")
            s, res = _run(args.rounds, fault_plan=nan_plan,
                          on_divergence="rollback", checkpoint_dir=ck,
                          checkpoint_every=2)
            assert not res.diverged and res.rounds_run == args.rounds
            rb_s = min(rb_s, s)
    # The replay redoes (nan_round - restored) rounds of real compute;
    # price that at the baseline per-round rate so the reported number
    # is the restore machinery itself, not the replayed training.
    replayed = nan_round - (nan_round - 1) // 2 * 2
    per_round = base_s / args.rounds
    result["rollback_run_s"] = rb_s
    result["rollback_restore_s"] = max(
        0.0, rb_s - base_s - replayed * per_round)
    print(f"nan+rollback run: {rb_s:.3f} s "
          f"(restore machinery ~{result['rollback_restore_s']:.3f} s "
          f"after pricing {replayed} replayed rounds)", file=sys.stderr)

    blob = json.dumps(result, indent=2)
    with open(args.out, "w") as f:
        f.write(blob + "\n")
    print(blob)
    return 0


if __name__ == "__main__":
    sys.exit(main())
