"""Time the Pallas kernels against their XLA equivalents on the real
chip (VERDICT r3 #6), and Mosaic-AOT-compile the RDMA ring's sync path
for a multi-chip v5e topology.

Adopt-on-win policy: a kernel that cannot beat XLA stays a tested
library op and the production path keeps XLA; either way the measured
number is recorded in benchmarks/RESULTS.md ('Pallas kernel timings').

Run: ``python benchmarks/pallas_timing.py`` (~2 min on the v5e).
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import (DataConfig, ModelConfig, OptimConfig, ShardConfig,
                           default_income_csv)
from fedtpu.data import load_dataset
from fedtpu.data.sharding import pack_clients
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.metrics import confusion_matrix
from fedtpu.ops.pallas_kernels import (fused_eval_confusion,
                                       fused_mlp_forward,
                                       weighted_average_clients)
from fedtpu.parallel import make_mesh
from fedtpu.parallel.round import init_federated_state
from fedtpu.utils.timing import force_fetch, marginal_slope
from fedtpu.utils.trees import clone

NUM_CLIENTS = 8




def scan_over(fn_body, const):
    """Scan R applications of fn_body(carry-coupled) so per-call cost is
    slope-measurable; couples the carry so nothing hoists."""
    def gen(R):
        @jax.jit
        def f(c0):
            def body(c, _):
                out = fn_body(c)
                s = sum(jnp.sum(o) for o in jax.tree.leaves(out))
                return jax.tree.map(lambda t: t + 1e-20 * s, c), s
            c, ss = jax.lax.scan(body, c0, length=R)
            return ss[-1]
        return lambda: f(const)
    return gen


def main():
    ds = load_dataset(DataConfig(csv_path=default_income_csv()))
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    xd, yd, md = (jnp.asarray(packed.x), jnp.asarray(packed.y),
                  jnp.asarray(packed.mask))
    init_fn, apply_fn = build_model(
        ModelConfig(input_dim=ds.input_dim, num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    state = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                 init_fn, tx)
    params = clone(state["params"])
    p0 = jax.tree.map(lambda t: t[0], params)   # single-client params
    x_test = jnp.asarray(ds.x_test)
    out = {}

    # ---- 1. fused_mlp_forward vs XLA apply (the held-out eval shape)
    m_pal = marginal_slope(scan_over(
        lambda p: fused_mlp_forward(p, x_test), p0))
    m_xla = marginal_slope(scan_over(
        lambda p: apply_fn(p, x_test), p0))
    out["heldout_eval_forward"] = {"pallas_s": m_pal, "xla_s": m_xla}

    # ---- 2. weighted_average_clients vs the XLA weighted mean, on the
    # flat per-leaf stacks the aggregation actually reduces
    w = md.sum(axis=1).astype(jnp.float32)
    flat = jnp.concatenate(
        [l.reshape(NUM_CLIENTS, -1) for l in jax.tree.leaves(params)],
        axis=1)

    def xla_wavg(f):
        return (w @ f) / w.sum()

    m_pal_w = marginal_slope(scan_over(
        lambda f: weighted_average_clients(f, w), flat))
    m_xla_w = marginal_slope(scan_over(xla_wavg, flat))
    out["weighted_average"] = {"pallas_s": m_pal_w, "xla_s": m_xla_w,
                               "flat_dim": int(flat.shape[1])}

    # ---- 3. fused eval->confusion vs the XLA eval chain (in-round shape)
    m_pal_e = marginal_slope(scan_over(
        lambda p: fused_eval_confusion(p, xd, yd, md, ds.num_classes),
        params))
    # The XLA chain is fast enough (~2-5 us/iter) that the default
    # windows sink under dispatch jitter; widen them.
    m_xla_e = marginal_slope(scan_over(
        lambda p: jax.vmap(lambda pp, xx, yy, mm: confusion_matrix(
            yy, jnp.argmax(apply_fn(pp, xx), -1), mm,
            ds.num_classes))(p, xd, yd, md), params),
        lens=(2000, 10000), reps=6)
    out["eval_confusion"] = {"pallas_s": m_pal_e, "xla_s": m_xla_e}

    # ---- 4. Mosaic AOT compile of the ring sync path for 4 v5e chips
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from fedtpu.parallel.ring_pallas import pallas_ring_all_reduce_sum

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2x1")
    devs = np.asarray(topo.devices)[:4]
    ring_mesh = Mesh(devs.reshape(4), ("clients",))

    def ring_fn(t):
        return jax.shard_map(
            lambda u: pallas_ring_all_reduce_sum(u[0], "clients", 4,
                                                 interpret=False)[None],
            mesh=ring_mesh, in_specs=P("clients"),
            out_specs=P("clients"))(t)

    sharded = jax.ShapeDtypeStruct(
        (4, 1024), jnp.float32,
        sharding=NamedSharding(ring_mesh, P("clients")))
    try:
        jax.jit(ring_fn).lower(sharded).compile()
        out["ring_sync_aot_v5e_2x2"] = True
    except Exception as e:
        out["ring_sync_aot_v5e_2x2"] = False
        out["ring_sync_aot_error"] = f"{type(e).__name__}: {e}"[:500]

    print(json.dumps(out, indent=2, default=float))
    for name, row in out.items():
        if isinstance(row, dict) and "pallas_s" in row:
            r = row["xla_s"] / row["pallas_s"]
            verdict = ("pallas wins" if r > 1.15
                       else "xla wins" if r < 0.87 else "tie")
            print(f"[pallas] {name}: pallas {row['pallas_s']*1e6:.2f} us vs "
                  f"xla {row['xla_s']*1e6:.2f} us -> {verdict}")
    print(f"[pallas] ring sync path AOT Mosaic compile for v5e 2x2: "
          f"{'ok' if out['ring_sync_aot_v5e_2x2'] else 'FAILED'}")


if __name__ == "__main__":
    main()
