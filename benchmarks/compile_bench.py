"""Cold vs warm time-to-first-round through the serialized-executable cache.

ROUND5 measured the sweep's cold compile at 90-207 s on a contended box
against a 29 s warm-run win — compilation, not compute, dominates short
runs. This benchmark captures the remedy's two numbers for the round
program family:

    cold: trace + XLA compile (stored to a fresh ProgramCache) + the
          first chunk of rounds executed to completion;
    warm: a FRESH ProgramCache instance on the same directory
          deserializes the executable (no trace, no XLA) + the same
          first chunk from the same initial state.

The warm path must be at least --min-speedup (default 5) times faster
to first-round completion, and its outputs must be BITWISE equal to the
fresh-compiled program's — a deserialized executable is the same
program, not an approximation of it. A violation crashes the benchmark
rather than recording the number.

Run: ``python benchmarks/compile_bench.py`` (~10 s on the CPU box).
Emits bench.py-style output: detail lines on stderr, one full JSON blob
last on stdout (and to --out).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--preset", default="income-8")
    ap.add_argument("--synthetic-rows", type=int, default=2048,
                    help="synthetic dataset rows (0 = the preset's real "
                         "data; default keeps the benchmark hermetic)")
    ap.add_argument("--rounds-per-step", type=int, default=4,
                    help="chunk width of the benchmarked round program")
    ap.add_argument("--cache", default=None, metavar="DIR",
                    help="cache dir (default: fresh temp dir, so the cold "
                         "leg is genuinely cold)")
    ap.add_argument("--min-speedup", type=float, default=5.0,
                    help="required cold/warm time-to-first-round ratio")
    ap.add_argument("--out", default="BENCH_COMPILE.json",
                    help="file the JSON result is written to")
    args = ap.parse_args(argv)

    import jax

    from fedtpu.compilation import (ProgramCache, program_config_slice,
                                    program_fingerprint)
    from fedtpu.config import get_preset
    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.utils.trees import clone

    cfg = get_preset(args.preset)
    if args.synthetic_rows:
        cfg = dataclasses.replace(cfg, data=dataclasses.replace(
            cfg.data, csv_path=None, dataset_name=None,
            synthetic_rows=args.synthetic_rows))
    exp = build_experiment(cfg)
    step = exp.make_step(args.rounds_per_step)
    key = program_fingerprint(
        "round", config=program_config_slice(cfg), mesh=exp.mesh,
        args=(exp.state, exp.batch),
        extra={"rounds_per_step": int(args.rounds_per_step)})

    cache_dir = args.cache or tempfile.mkdtemp(prefix="fedtpu-compile-bench-")

    # COLD leg: trace + XLA compile (+ store) + first chunk of rounds.
    # The state is cloned per call: the round step donates its state
    # buffer, and both legs must start from identical bits.
    cache = ProgramCache(cache_dir)
    t0 = time.perf_counter()
    entry = cache.get_or_compile(key, step, exp.state, exp.batch,
                                 label="bench-round")
    cold_compile_s = time.perf_counter() - t0
    if entry.warm:
        raise SystemExit("compile_bench: cache dir already holds this "
                         "program; point --cache at a fresh dir")
    out_cold = entry.compiled(clone(exp.state), exp.batch)
    jax.block_until_ready(out_cold)
    cold_total_s = time.perf_counter() - t0

    # WARM leg: a fresh ProgramCache instance deserializes — no trace,
    # no XLA compile — then runs the same chunk from the same state.
    t0 = time.perf_counter()
    warm = ProgramCache(cache_dir).load(key)
    if warm is None:
        raise SystemExit("compile_bench: warm load failed (serialization "
                         "unsupported on this backend?)")
    warm_lookup_s = time.perf_counter() - t0
    out_warm = warm.compiled(clone(exp.state), exp.batch)
    jax.block_until_ready(out_warm)
    warm_total_s = time.perf_counter() - t0

    # The deserialized executable is the SAME program: bitwise equality
    # over every output leaf (new state + metrics), not approximate.
    pairs = list(zip(jax.tree.leaves(out_cold), jax.tree.leaves(out_warm)))
    bitwise_equal = bool(pairs) and all(
        np.array_equal(np.asarray(a), np.asarray(b)) for a, b in pairs)
    if not bitwise_equal:
        raise SystemExit("compile_bench: deserialized program diverged "
                         "bitwise from the fresh-compiled one")

    speedup = cold_total_s / warm_total_s
    if speedup < args.min_speedup:
        raise SystemExit(
            f"compile_bench: warm time-to-first-round only {speedup:.2f}x "
            f"faster than cold (need >= {args.min_speedup}x): "
            f"cold {cold_total_s:.3f} s vs warm {warm_total_s:.3f} s")

    result = {
        "metric": "compile_cache_time_to_first_round",
        "preset": args.preset,
        "rounds_per_step": int(args.rounds_per_step),
        "key": key,
        "cache_dir": cache_dir,
        "payload_bytes": int(entry_meta_bytes(cache, key)),
        "cold_compile_s": round(cold_compile_s, 4),
        "cold_time_to_first_round_s": round(cold_total_s, 4),
        "warm_lookup_ms": round(warm_lookup_s * 1e3, 2),
        "warm_time_to_first_round_s": round(warm_total_s, 4),
        "speedup_time_to_first_round": round(speedup, 2),
        "speedup_compile_vs_lookup": round(
            cold_compile_s / max(warm_lookup_s, 1e-9), 2),
        "bitwise_equal": bitwise_equal,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
    }
    detail = [
        f"[compile_bench] cold: compile {cold_compile_s:.3f} s, "
        f"first round done at {cold_total_s:.3f} s",
        f"[compile_bench] warm: deserialize {warm_lookup_s * 1e3:.1f} ms, "
        f"first round done at {warm_total_s:.3f} s",
        f"[compile_bench] time-to-first-round speedup {speedup:.1f}x "
        f"(compile-vs-lookup {result['speedup_compile_vs_lookup']:.0f}x), "
        f"outputs bitwise equal: {bitwise_equal}",
    ]
    for line in detail:
        print(line, file=sys.stderr)
    blob = json.dumps(result)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    sys.stderr.flush()
    print(blob, flush=True)
    return 0


def entry_meta_bytes(cache, key) -> int:
    meta = cache._read_meta(cache._paths(key)[1])
    return int((meta or {}).get("payload_bytes") or 0)


if __name__ == "__main__":
    raise SystemExit(main())
