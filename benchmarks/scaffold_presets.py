"""SCAFFOLD + adaptive clip on the STANDING presets (VERDICT r4 next #5).

Round 4 proved both algorithms on bespoke demos (a label-sorted logistic
task for SCAFFOLD's 1.40x stationarity win; unit-test oracles for the
clip). This script puts numbers on the framework's own benchmark config —
`income-32-noniid` (32 dirichlet-skewed clients on the real income CSV) —
recorded honestly even where the answer is null:

1. FedAvg vs FedProx(mu=0.1) vs SCAFFOLD at local_steps=5, uniform
   weighting, 300 rounds: final accuracies AND the drift observable the
   round-4 demo established — the stationarity floor, measured as the
   mean L2 norm of the global model's per-10-round movement over the last
   third of training (accuracy alone is the wrong observable: all three
   plateau on this task).
2. Adaptive DP clipping on the same preset: noise-free quantile tracking
   (where does the clip settle from a deliberately-wrong init?) and the
   full DP config (z=0.5, count z=1.0) vs a fixed clip at the same z —
   accuracy + epsilon + final clip.

Usage: python benchmarks/scaffold_presets.py [--json OUT.jsonl]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from fedtpu.config import RunConfig, get_preset

ROUNDS = 300
CHUNK = 10


def _base_cfg(constant_lr=False, **fed_kw):
    """The standing preset at E=5/uniform. ``constant_lr`` disables the
    preset's StepLR(30, 0.5): stepped once per LOCAL update, E=5 x 300
    rounds halves the LR 50 times (0.004 * 2^-50 ~ 4e-18), so by round
    300 NO algorithm can move and every drift floor collapses to ~0 —
    the schedule, not the aggregation rule, is the observable. The
    scheduled rows are still recorded (they are the preset's semantics);
    the constant-LR rows are where the floor means something."""
    base = get_preset("income-32-noniid")
    optim = (dataclasses.replace(base.optim, steplr_step_size=10 ** 9)
             if constant_lr else base.optim)
    return dataclasses.replace(
        base, optim=optim,
        fed=dataclasses.replace(base.fed, rounds=ROUNDS,
                                weighting="uniform", local_steps=5,
                                termination_patience=10 ** 9, **fed_kw),
        run=RunConfig(rounds_per_step=CHUNK, log_every=10 ** 9,
                      eval_test_every=ROUNDS))


def bench_drift():
    import jax

    from fedtpu.orchestration.loop import build_experiment
    from fedtpu.parallel.round import global_params
    from fedtpu.utils.timing import force_fetch

    rows = []
    for label, constant_lr, fed_kw in (
            ("fedavg E=5", False, {}),
            ("fedprox mu=0.1 E=5", False, {"prox_mu": 0.1}),
            ("scaffold E=5", False, {"scaffold": True}),
            ("fedavg E=5 constant-lr", True, {}),
            ("fedprox mu=0.1 E=5 constant-lr", True, {"prox_mu": 0.1}),
            ("scaffold E=5 constant-lr", True, {"scaffold": True}),
    ):
        cfg = _base_cfg(constant_lr=constant_lr, **fed_kw)
        exp = build_experiment(cfg)
        step = exp.make_step(CHUNK)
        state, batch = exp.state, exp.batch
        move_norms = []          # ||g_{t+10} - g_t|| per chunk
        g_prev = jax.tree.map(np.asarray, global_params(state))
        t0 = time.perf_counter()
        metrics = None
        for _ in range(ROUNDS // CHUNK):
            state, metrics = step(state, batch)
            g = jax.tree.map(np.asarray, global_params(state))
            move_norms.append(float(np.sqrt(sum(
                float(np.sum((a - b) ** 2))
                for a, b in zip(jax.tree.leaves(g),
                                jax.tree.leaves(g_prev))))))
            g_prev = g
        force_fetch(metrics["client_mean"]["accuracy"])
        wall = time.perf_counter() - t0
        acc = float(np.asarray(
            metrics["client_mean"]["accuracy"]).ravel()[-1])
        pooled = float(np.asarray(metrics["pooled"]["accuracy"]).ravel()[-1])
        tm = exp.eval_step(global_params(state),
                           exp.dataset.x_test, exp.dataset.y_test)
        floor = float(np.mean(move_norms[-len(move_norms) // 3:]))
        rows.append({"row": "drift", "label": label,
                     "client_mean_accuracy": acc,
                     "pooled_accuracy": pooled,
                     "test_accuracy": float(np.asarray(tm["accuracy"])),
                     "stationarity_floor": floor,
                     "move_norm_first": move_norms[0],
                     "wall_s": wall})
        print(f"[scaffold_presets] {label}: client-mean {acc:.4f}, pooled "
              f"{pooled:.4f}, test {rows[-1]['test_accuracy']:.4f}, "
              f"floor {floor:.4e} (first chunk {move_norms[0]:.3e})  "
              f"({wall:.1f}s)", file=sys.stderr)
    return rows


def bench_adaptive_clip():
    from fedtpu.orchestration.loop import run_experiment

    rows = []

    def run(label, **fed_kw):
        cfg = _base_cfg(**fed_kw)
        res = run_experiment(cfg, verbose=False)
        dp = res.privacy_spent()
        row = {"row": "adaptive_clip", "label": label,
               "client_mean_accuracy": res.global_metrics["accuracy"][-1],
               "test_accuracy": res.test_metrics["accuracy"][-1],
               **({"final_dp_clip": res.final_dp_clip}
                  if res.final_dp_clip is not None else {}),
               **({"epsilon": dp["epsilon"]} if dp else {})}
        rows.append(row)
        print(f"[scaffold_presets] {label}: client-mean "
              f"{row['client_mean_accuracy']:.4f}, test "
              f"{row['test_accuracy']:.4f}"
              + (f", final clip {row['final_dp_clip']:.4f}"
                 if "final_dp_clip" in row else "")
              + (f", epsilon {row['epsilon']:.2f}" if "epsilon" in row
                 else ""), file=sys.stderr)

    # Noise-free quantile tracking from a deliberately-10x-wrong init.
    # Under the preset's StepLR the update norms themselves decay to ~0,
    # so the clip correctly tracks them there; the constant-LR row is
    # where the settled clip is a meaningful norm scale.
    run("adaptive clip, noise-free, init 1.0",
        dp_clip_norm=1.0, dp_adaptive_clip=True)
    run("adaptive clip, noise-free, init 1.0, constant-lr",
        constant_lr=True, dp_clip_norm=1.0, dp_adaptive_clip=True)
    # Full DP: fixed clip vs adaptive at the same per-round z.
    run("fixed clip 0.1, z=0.5",
        dp_clip_norm=0.1, dp_noise_multiplier=0.5)
    run("adaptive clip init 1.0, z=0.5 (count z=1.0)",
        dp_clip_norm=1.0, dp_noise_multiplier=0.5,
        dp_count_noise_multiplier=1.0, dp_adaptive_clip=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    rows = bench_drift() + bench_adaptive_clip()
    out = open(args.json, "w") if args.json else None
    for r in rows:
        line = json.dumps(r, default=float)
        print(line)
        if out:
            out.write(line + "\n")
    if out:
        out.close()


if __name__ == "__main__":
    main()
