"""Per-feature overhead of the aggregation modes, measured on the income
MLP at 8 clients (the headline bench.py shape): sec/round at
rounds_per_step=100 for each mode vs the plain weighted mean.

Every mode runs inside the same compiled multi-round scan, so this is the
true marginal cost of the richer aggregation math (server optimizers, DP
clip+noise, int8 quantize/gather, coordinate-wise order statistics) on the
hot path. Prints one JSON line per mode.

Usage: python benchmarks/feature_overhead.py [--reps 30] [--rounds-per-step 100]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from fedtpu.config import DataConfig, ModelConfig, OptimConfig, ShardConfig, \
    default_income_csv
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.ops.server_opt import make_server_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state

NUM_CLIENTS = 8

MODES = {
    "mean": {},
    "local_steps_5": dict(local_steps=5),
    "fedadam": dict(server_opt="fedadam"),
    "dp": dict(dp_clip_norm=1.0, dp_noise_multiplier=0.1,
               weighting="uniform"),
    "int8": dict(compress="int8"),
    "median": dict(robust_aggregation="median", weighting="uniform"),
    "trimmed_mean": dict(robust_aggregation="trimmed_mean",
                         weighting="uniform"),
    "byzantine_2": dict(byzantine_clients=2),
}


def bench_mode(name: str, kw: dict, ds, reps: int, rps: int,
               peak_flops: float) -> dict:
    kw = dict(kw)
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())

    server = None
    if "server_opt" in kw:
        server = make_server_optimizer(kw.pop("server_opt"),
                                       learning_rate=0.02)
    state_server = server
    if state_server is None and kw.get("dp_clip_norm", 0) > 0:
        from fedtpu.ops.server_opt import identity_server_optimizer
        state_server = identity_server_optimizer()
    state = init_federated_state(
        jax.random.key(0), mesh, NUM_CLIENTS, init_fn, tx,
        server_opt=state_server,
        shared_start=kw.get("compress", "none") != "none")
    step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                          rounds_per_step=rps, server_opt=server, **kw)

    # Fetch-forced timing + flops floor — see fedtpu.utils.timing docstring
    # for the methodology (round-1 postmortem). SEVERAL independent samples
    # per mode (each itself min-of-3 windows): the tunneled transport's
    # dispatch share jitters by ~±15%, and a single sample let added work
    # appear cheaper than the baseline (review r2 weak #5) — the caller
    # compares BANDS, not points.
    from fedtpu.utils.timing import compile_with_flops, timed_rounds

    step, flops_per_round = compile_with_flops(step, state, batch)
    samples = []
    for _ in range(5):
        sec, state, m = timed_rounds(step, state, batch, reps, rps,
                                     peak_flops, flops_per_round, label=name)
        samples.append(sec)
    samples.sort()
    return {"mode": name,
            "sec_per_round": float(f"{samples[len(samples) // 2]:.4g}"),
            "sec_per_round_range": [float(f"{samples[0]:.4g}"),
                                    float(f"{samples[-1]:.4g}")],
            "rounds_per_step": rps,
            "backend": mesh.devices.ravel()[0].platform}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--rounds-per-step", type=int, default=100)
    args = ap.parse_args()

    from fedtpu.utils.timing import measured_peak_flops

    peak = measured_peak_flops(dtype="float32")
    ds = load_tabular_dataset(DataConfig(csv_path=default_income_csv()))
    base = None
    for name, kw in MODES.items():
        row = bench_mode(name, kw, ds, args.reps, args.rounds_per_step, peak)
        if name == "mean":
            base = row
        lo, hi = row["sec_per_round_range"]
        blo, bhi = base["sec_per_round_range"]
        row["vs_mean"] = float(
            f"{row['sec_per_round'] / base['sec_per_round']:.3g}")
        # Ratio band from the two sample bands; a row only claims a real
        # overhead (or saving) when the bands do NOT overlap. Overlapping
        # bands => the difference is within dispatch noise, and the row
        # says so instead of printing a meaningless sub-1.0 ratio.
        row["vs_mean_range"] = [float(f"{lo / bhi:.3g}"),
                                float(f"{hi / blo:.3g}")]
        row["significant"] = bool(lo > bhi or hi < blo)
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
