"""Client-scaling benchmark: sec/round at 1 -> 8 -> 32 clients (BASELINE.md
config matrix), plus the CIFAR-10 ConvNet payload stress config.

Prints one JSON line per config. On a single chip, clients beyond the device
count vmap-oversubscribe (the analogue of `mpirun -np 32` on one node); on a
v4-8/v4-32 the same code lays one client per core.

Usage: python benchmarks/scaling.py [--rounds 20] [--rounds-per-step 10]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from fedtpu.config import (DataConfig, ModelConfig, OptimConfig, ShardConfig,
                           default_income_csv)
from fedtpu.data.cifar10 import load_cifar10
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def bench_config(name: str, ds, model_cfg: ModelConfig, num_clients: int,
                 rounds: int, rounds_per_step: int,
                 peak_flops: float) -> dict:
    mesh = make_mesh(num_clients=num_clients)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=num_clients))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(model_cfg)
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, num_clients,
                                 init_fn, tx)
    step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                          rounds_per_step=rounds_per_step)

    # Fetch-forced timing + flops floor — see fedtpu.utils.timing docstring
    # for the methodology (round-1 postmortem).
    from fedtpu.utils.timing import compile_with_flops, timed_rounds

    step, flops_per_round = compile_with_flops(step, state, batch)
    iters = max(3, rounds // rounds_per_step)
    sec, state, m = timed_rounds(step, state, batch, iters, rounds_per_step,
                                 peak_flops, flops_per_round, label=name)
    return {
        "config": name, "num_clients": num_clients,
        "sec_per_round": round(sec, 9),
        "devices": len(mesh.devices.ravel()),
        "backend": mesh.devices.ravel()[0].platform,
        "train_rows": int(len(ds.x_train)),
        "params_dtype": model_cfg.param_dtype,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--rounds-per-step", type=int, default=10)
    ap.add_argument("--skip-cifar", action="store_true")
    args = ap.parse_args()

    from fedtpu.utils.timing import measured_peak_flops

    peak = measured_peak_flops(dtype="float32")
    income = load_tabular_dataset(DataConfig(csv_path=default_income_csv()))
    mlp = ModelConfig(input_dim=income.input_dim,
                      num_classes=income.num_classes)
    for c in (1, 8, 32):
        print(json.dumps(bench_config(f"income-mlp-{c}", income, mlp, c,
                                      args.rounds, args.rounds_per_step,
                                      peak)),
              flush=True)

    if not args.skip_cifar:
        cifar = load_cifar10(synthetic_rows=4096)
        conv = ModelConfig(kind="convnet", num_classes=10,
                           hidden_sizes=(256,), compute_dtype="bfloat16")
        print(json.dumps(bench_config("cifar10-convnet-32", cifar, conv, 32,
                                      args.rounds, args.rounds_per_step,
                                      peak)),
              flush=True)


if __name__ == "__main__":
    main()
