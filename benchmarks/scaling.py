"""Client-scaling benchmark: sec/round at 1 -> 8 -> 32 clients (BASELINE.md
config matrix), plus the CIFAR-10 ConvNet payload stress config — and, with
``--scale``, the population sweep (10k -> 1M simulated clients through the
cohort store, docs/scaling.md).

Prints one JSON line per config. On a single chip, clients beyond the device
count vmap-oversubscribe (the analogue of `mpirun -np 32` on one node); on a
v4-8/v4-32 the same code lays one client per core.

``--scale`` runs each (total_clients, store backend) row in its OWN
subprocess so per-row peak RSS (``ru_maxrss``) is independent — the point of
the artifact is that peak host+device memory is flat in total client count
(cohort-size dependent only), so rows must not inherit each other's
high-water mark. Rows land in ``BENCH_SCALE.json``.

Usage: python benchmarks/scaling.py [--rounds 20] [--rounds-per-step 10]
       python benchmarks/scaling.py --scale [--total-clients 10000,100000,1000000]
           [--store memory,mmap] [--cohort-size 64] [--out BENCH_SCALE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import jax
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from fedtpu.config import (DataConfig, ModelConfig, OptimConfig, ShardConfig,
                           default_income_csv)
from fedtpu.data.cifar10 import load_cifar10
from fedtpu.data.sharding import pack_clients
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh, client_sharding
from fedtpu.parallel.round import build_round_fn, init_federated_state


def bench_config(name: str, ds, model_cfg: ModelConfig, num_clients: int,
                 rounds: int, rounds_per_step: int,
                 peak_flops: float) -> dict:
    mesh = make_mesh(num_clients=num_clients)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=num_clients))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(model_cfg)
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, num_clients,
                                 init_fn, tx)
    step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                          rounds_per_step=rounds_per_step)

    # Fetch-forced timing + flops floor — see fedtpu.utils.timing docstring
    # for the methodology (round-1 postmortem).
    from fedtpu.utils.timing import compile_with_flops, timed_rounds

    step, flops_per_round = compile_with_flops(step, state, batch)
    iters = max(3, rounds // rounds_per_step)
    sec, state, m = timed_rounds(step, state, batch, iters, rounds_per_step,
                                 peak_flops, flops_per_round, label=name)
    return {
        "config": name, "num_clients": num_clients,
        "sec_per_round": round(sec, 9),
        "devices": len(mesh.devices.ravel()),
        "backend": mesh.devices.ravel()[0].platform,
        "train_rows": int(len(ds.x_train)),
        "params_dtype": model_cfg.param_dtype,
    }


# ------------------------------------------------------------------ scale

# memory-backend rows above this population are skipped by default: the
# apparent store (total_clients x record_bytes) stops fitting comfortably
# even though calloc keeps untouched pages virtual.
MEMORY_STORE_CAP = 200_000


def _device_peak_reported() -> int:
    """Peak device allocation if the backend reports it (TPU/GPU); CPU
    returns 0 and the sampled live-buffer high-water mark stands in."""
    stats = {}
    dev = jax.local_devices()[0]
    if hasattr(dev, "memory_stats"):
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
    return int(stats.get("peak_bytes_in_use") or 0)


class _LiveBufferSampler:
    """Background thread tracking max(sum of live jax array bytes) — the
    CPU stand-in for an HBM high-water mark."""

    def __init__(self, interval_s: float = 0.05):
        import threading
        self.peak = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, args=(interval_s,),
                                   daemon=True)

    def _run(self, interval_s):
        while not self._stop.is_set():
            try:
                now = sum(int(a.nbytes) for a in jax.live_arrays())
            except Exception:
                now = 0
            self.peak = max(self.peak, now)
            self._stop.wait(interval_s)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join()


def bench_scale_row(total_clients: int, cohort_size: int, store: str,
                    rounds: int, store_path: str | None) -> dict:
    """One cohort-store row: run `rounds` full cohort rounds over a
    `total_clients` simulated population and report peak host + device
    memory. Meant to run in a fresh subprocess (see `main`)."""
    import resource

    from fedtpu.config import ExperimentConfig, FedConfig, RunConfig
    from fedtpu.cohort.scheduler import run_cohort_experiment
    from fedtpu.telemetry.metrics import default_registry

    cfg = ExperimentConfig(
        # Synthetic tabular rows: the sweep measures state scale, not data
        # scale, so the sample pool stays fixed while clients grow.
        data=DataConfig(csv_path=None, synthetic_rows=4096),
        shard=ShardConfig(num_clients=total_clients),
        model=ModelConfig(input_dim=14, num_classes=2, hidden_sizes=(8,)),
        optim=OptimConfig(),
        fed=FedConfig(rounds=rounds, cohort_size=cohort_size,
                      client_store=store, client_store_path=store_path),
        run=RunConfig(log_every=max(1, rounds), rounds_per_step=1),
    )
    t0 = time.perf_counter()
    with _LiveBufferSampler() as sampler:
        res = run_cohort_experiment(cfg, verbose=False)
    wall = time.perf_counter() - t0
    reg = default_registry()
    ru = resource.getrusage(resource.RUSAGE_SELF)
    # Linux reports ru_maxrss in KiB.
    peak_rss = int(ru.ru_maxrss) * 1024
    return {
        "config": f"cohort-{store}-{total_clients}",
        "total_clients": total_clients,
        "cohort_size": cohort_size,
        "store": store,
        "rounds": res.rounds_run,
        "sec_per_round": round(float(np.mean(res.sec_per_round)), 9),
        "wall_s": round(wall, 3),
        "peak_rss_bytes": peak_rss,
        "device_peak_bytes": _device_peak_reported() or sampler.peak,
        "store_apparent_bytes": int(
            reg.gauge("client_store_apparent_bytes").value),
        "store_resident_bytes": int(
            reg.gauge("client_store_resident_bytes").value),
        "backend": jax.local_devices()[0].platform,
    }


def run_scale_sweep(args) -> list:
    """Fan the sweep out one row per subprocess (independent ru_maxrss);
    each child re-enters this script with the hidden --scale-row flag."""
    totals = [int(t) for t in str(args.total_clients).split(",") if t]
    stores = [s.strip() for s in str(args.store).split(",") if s.strip()]
    rows = []
    for total in totals:
        for store in stores:
            if store == "memory" and total > MEMORY_STORE_CAP:
                print(f"# skip cohort-memory-{total}: memory backend capped "
                      f"at {MEMORY_STORE_CAP} clients (use mmap)",
                      file=sys.stderr, flush=True)
                continue
            with tempfile.TemporaryDirectory() as tmp:
                cmd = [sys.executable, os.path.abspath(__file__),
                       "--scale-row", "--total-clients", str(total),
                       "--store", store,
                       "--cohort-size", str(args.cohort_size),
                       "--scale-rounds", str(args.scale_rounds)]
                if store == "mmap":
                    cmd += ["--store-path",
                            os.path.join(tmp, "client_store.bin")]
                out = subprocess.run(cmd, capture_output=True, text=True)
                if out.returncode != 0:
                    raise RuntimeError(
                        f"scale row {store}/{total} failed:\n"
                        + out.stderr[-4000:])
                row = json.loads(out.stdout.strip().splitlines()[-1])
            rows.append(row)
            print(json.dumps(row), flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--rounds-per-step", type=int, default=10)
    ap.add_argument("--skip-cifar", action="store_true")
    # Population sweep through the cohort store (docs/scaling.md).
    ap.add_argument("--scale", action="store_true",
                    help="run the cohort population sweep instead of the "
                         "vmap config matrix; writes --out")
    ap.add_argument("--total-clients", default="10000,100000,1000000",
                    help="comma list of simulated population sizes")
    ap.add_argument("--store", default="memory,mmap",
                    help="comma list of store backends to sweep")
    ap.add_argument("--cohort-size", type=int, default=64)
    ap.add_argument("--scale-rounds", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="write sweep rows to this JSON file "
                         "(default BENCH_SCALE.json next to this script)")
    ap.add_argument("--scale-row", action="store_true",
                    help=argparse.SUPPRESS)   # internal: one row, this proc
    ap.add_argument("--store-path", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.scale_row:
        row = bench_scale_row(int(args.total_clients), args.cohort_size,
                              args.store, args.scale_rounds, args.store_path)
        print(json.dumps(row), flush=True)
        return

    if args.scale:
        rows = run_scale_sweep(args)
        out = args.out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..",
            "BENCH_SCALE.json")
        with open(out, "w") as f:
            json.dump({"rows": rows, "cohort_size": args.cohort_size,
                       "rounds_per_row": args.scale_rounds}, f, indent=2)
            f.write("\n")
        print(f"# wrote {os.path.normpath(out)}", file=sys.stderr)
        return

    from fedtpu.utils.timing import measured_peak_flops

    peak = measured_peak_flops(dtype="float32")
    income = load_tabular_dataset(DataConfig(csv_path=default_income_csv()))
    mlp = ModelConfig(input_dim=income.input_dim,
                      num_classes=income.num_classes)
    for c in (1, 8, 32):
        print(json.dumps(bench_config(f"income-mlp-{c}", income, mlp, c,
                                      args.rounds, args.rounds_per_step,
                                      peak)),
              flush=True)

    if not args.skip_cifar:
        cifar = load_cifar10(synthetic_rows=4096)
        conv = ModelConfig(kind="convnet", num_classes=10,
                           hidden_sizes=(256,), compute_dtype="bfloat16")
        print(json.dumps(bench_config("cifar10-convnet-32", cifar, conv, 32,
                                      args.rounds, args.rounds_per_step,
                                      peak)),
              flush=True)


if __name__ == "__main__":
    main()
