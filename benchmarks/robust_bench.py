"""Price of the poisoning defenses (docs/robustness.md) on the hot path.

Three rows, each a clean-traffic overhead question — what does arming a
defense cost when nobody is attacking:

    screen_tick:    steady-state wall per driven async tick with the
                    in-jit screen (norm ring + cosine test) armed vs
                    off, same arrivals, same model. The screen adds one
                    norm + one dot per client slot plus the rolling
                    median ring update.
    cohort_robust:  wall per cohort round for robust='median' and
                    'trimmed_mean' vs the plain psum mean, end-to-end
                    through run_experiment (the sort network per
                    coordinate is the cost).
    defense_sim:    wall-clock of the pinned golden campaign
                    (fedtpu.robust.defense_sim) plus its containment
                    summary — the price of the tier-1 gate itself.

Run: ``python benchmarks/robust_bench.py`` (~2 min on the CPU box).
Emits bench.py-style output: detail lines on stderr, one full JSON blob
last on stdout (and to --out); raw committed rows live in
``benchmarks/robust_bench.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def _screen_tick_row(ticks, warmup):
    import jax

    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.data.tabular import synthetic_income_like
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import async_fed, client_sharding, make_mesh

    C = 8
    x, y = synthetic_income_like(512, 6, 2, seed=0)
    packed = pack_clients(x, y, ShardConfig(num_clients=C, shuffle=False))
    init_fn, apply_fn = build_model(ModelConfig(input_dim=6,
                                                hidden_sizes=(64, 32)))
    tx = build_optimizer(OptimConfig())
    mesh = make_mesh(num_clients=C)
    batch = {k: jax.device_put(v, client_sharding(mesh)) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    arr = np.ones((1, C), np.float32)

    def timed(screen):
        state = async_fed.init_async_state(
            jax.random.key(0), mesh, C, init_fn, tx, same_init=True,
            screen_window=64 if screen else 0)
        step = async_fed.build_async_round_fn(
            mesh, apply_fn, tx, 2, driven=True, screen=screen)
        for _ in range(warmup):  # compile + screen warmup out of the window
            state, m = step(state, batch, arr)
        jax.block_until_ready(m["staleness"])
        t0 = time.perf_counter()
        for _ in range(ticks):
            state, m = step(state, batch, arr)
        # Completion proof: host value dependent on the full chain.
        screened = (float(np.asarray(m["screened"]).sum())
                    if screen else 0.0)
        jax.block_until_ready(m["staleness"])
        wall = time.perf_counter() - t0
        return wall / ticks, screened

    off_s, _ = timed(False)
    on_s, screened = timed(True)
    assert screened == 0.0, "screen fired on clean traffic"
    return {"row": "screen_tick", "clients": C, "ticks": ticks,
            "screen_window": 64,
            "off_s_per_tick": off_s, "on_s_per_tick": on_s,
            "overhead_pct": (on_s - off_s) / off_s * 100.0,
            "false_positives": int(screened)}


def _cohort_robust_row(rounds, reps):
    from fedtpu.config import (DataConfig, ExperimentConfig, FedConfig,
                               RunConfig, ShardConfig)
    from fedtpu.orchestration.loop import run_experiment

    def wall(robust):
        best = float("inf")
        acc = None
        for _ in range(reps):
            cfg = ExperimentConfig(
                data=DataConfig(csv_path=None, synthetic_rows=512),
                shard=ShardConfig(num_clients=32),
                fed=FedConfig(rounds=rounds, weighting="uniform",
                              cohort_size=8, robust_aggregation=robust),
                run=RunConfig(),
            )
            t0 = time.perf_counter()
            res = run_experiment(cfg, verbose=False)
            best = min(best, time.perf_counter() - t0)
            acc = float(res.pooled_metrics["accuracy"][-1])
        return best / rounds, acc

    none_s, none_acc = wall("none")
    med_s, med_acc = wall("median")
    trim_s, trim_acc = wall("trimmed_mean")
    return {"row": "cohort_robust", "clients": 32, "cohort": 8,
            "rounds": rounds,
            "mean_s_per_round": none_s, "median_s_per_round": med_s,
            "trimmed_mean_s_per_round": trim_s,
            "median_overhead_pct": (med_s - none_s) / none_s * 100.0,
            "trimmed_overhead_pct": (trim_s - none_s) / none_s * 100.0,
            "accuracy": {"mean": none_acc, "median": med_acc,
                         "trimmed_mean": trim_acc}}


def _defense_sim_row():
    from fedtpu.robust.defense_sim import simulate
    t0 = time.perf_counter()
    out = simulate()
    wall = time.perf_counter() - t0
    s = out["summary"]
    return {"row": "defense_sim", "wall_s": wall,
            "arrivals": s["arrivals"], "ticks": s["ticks"],
            "decision_lines": len(out["lines"]),
            "attackers": len(s["attackers"]),
            "quarantined_attackers": len(s["quarantined_attackers"]),
            "quarantined_honest": len(s["quarantined_honest"]),
            "eval_accuracy": s["eval_accuracy"]}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ticks", type=int, default=60,
                    help="timed driven async ticks per screen branch")
    ap.add_argument("--rounds", type=int, default=20,
                    help="cohort rounds per robust rule")
    ap.add_argument("--reps", type=int, default=2,
                    help="cohort wall-clock reps; best-of is reported")
    ap.add_argument("--out", default="BENCH_ROBUST.json")
    args = ap.parse_args(argv)

    rows = []
    for fn, kw in ((_screen_tick_row, dict(ticks=args.ticks, warmup=12)),
                   (_cohort_robust_row, dict(rounds=args.rounds,
                                             reps=args.reps)),
                   (_defense_sim_row, {})):
        row = fn(**kw)
        rows.append(row)
        print(json.dumps(row), file=sys.stderr)

    result = {"rows": rows}
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
