"""Serving front-end under heavy traffic — the ROADMAP item 5 artifact.

Replays a heavy-tailed synthetic arrival trace (default: 1M simulated
users, 1M arrivals — Zipf user popularity x lognormal burstiness) through
the full ingestion path and reports the SLO numbers the serving layer
exists to measure:

- ``serving_inproc`` row: trace -> admission -> ServingEngine directly
  (the socket framing removed, everything else identical), the
  throughput-honest path for millions of arrivals. Reports p50/p99
  update-to-incorporation latency (VIRTUAL seconds — deterministic),
  sustained engine rounds/sec under load (WALL — throughput), arrivals
  ingested/sec, and the admission verdict counts.
- ``serving_socket`` row: a real ``run_server`` loop (background thread)
  + the loadgen over localhost TCP with a BOUNDED event count — measures
  protocol frames/sec and events/sec through the wire, so the socket
  tax is visible next to the in-process ceiling.

CPU-friendly by design (JAX_PLATFORMS=cpu): the engine cohort is small
and the model tiny — this benchmark measures the serving machinery, not
the model math (async_bench.py owns tick FLOP cost).

Usage: JAX_PLATFORMS=cpu python benchmarks/serving_bench.py \
           [--users 1000000] [--arrivals 1000000] [--json OUT.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def bench_inproc(args):
    from fedtpu.config import ServingConfig
    from fedtpu.serving.engine import ServingEngine
    from fedtpu.serving.traces import synthesize_trace

    header, t, user, lat = synthesize_trace(
        users=args.users, arrivals=args.arrivals, horizon_s=args.horizon,
        seed=args.seed)
    cfg = ServingConfig(cohort=args.cohort, buffer_size=args.buffer_size,
                        tick_interval_s=args.tick_interval,
                        flush_every=args.flush_every,
                        rate_limit=args.rate_limit,
                        max_pending=args.max_pending)
    eng = ServingEngine(cfg)
    # Warm the driven step outside the window (first call compiles).
    eng.offer(0.0, 0, 0.0)
    eng.drain()
    t0 = time.perf_counter()
    eng.offer_many(zip(user.tolist(), t.tolist(), lat.tolist()))
    eng.drain()
    wall = time.perf_counter() - t0
    s = eng.summary()
    lat_pct = s["update_to_incorporation"]
    row = {
        "row": "serving_inproc",
        "label": (f"trace {args.users} users / {args.arrivals} arrivals "
                  f"over {args.horizon}s (cohort={args.cohort}, "
                  f"M={args.buffer_size})"),
        "users": args.users,
        "arrivals": args.arrivals,
        "horizon_s": args.horizon,
        "cohort": args.cohort,
        "buffer_size": args.buffer_size,
        "ticks": s["ticks"],
        "incorporated": s["incorporated"],
        "version": s["version"],
        "admission": s["admission"],
        "update_to_incorporation": lat_pct,
        "wall_s": wall,
        "rounds_per_sec": s["ticks"] / wall if wall > 0 else 0.0,
        "arrivals_per_sec": args.arrivals / wall if wall > 0 else 0.0,
    }
    print(f"[serving_bench] inproc: {s['ticks']} ticks over "
          f"{args.arrivals} arrivals in {wall:.1f}s wall "
          f"({row['rounds_per_sec']:.1f} rounds/s, "
          f"{row['arrivals_per_sec']:.0f} arrivals/s); "
          f"update->incorporation p50 {lat_pct['p50_s']:.3f}s "
          f"p99 {lat_pct['p99_s']:.3f}s (virtual)", file=sys.stderr)
    return [row]


def bench_socket(args):
    from fedtpu.config import ServingConfig
    from fedtpu.serving.loadgen import run_loadgen
    from fedtpu.serving.server import run_server
    from fedtpu.serving.traces import synthesize_trace, write_trace

    n = min(args.socket_events, args.arrivals)
    with tempfile.TemporaryDirectory() as d:
        trace = os.path.join(d, "trace.jsonl")
        header, t, user, lat = synthesize_trace(
            users=args.users, arrivals=n, horizon_s=args.horizon,
            seed=args.seed)
        write_trace(trace, header, t, user, lat)
        pf = os.path.join(d, "port")
        cfg = ServingConfig(buffer_size=args.buffer_size,
                            cohort=args.cohort,
                            tick_interval_s=args.tick_interval,
                            flush_every=args.flush_every)
        th = threading.Thread(
            target=run_server,
            kwargs=dict(cfg=cfg, port_file=pf, once=True, verbose=False))
        th.start()
        res = run_loadgen(trace, port_file=pf, batch=args.batch)
        th.join(timeout=120)
    row = {
        "row": "serving_socket",
        "label": f"localhost socket, {n} events, batch={args.batch}",
        "events": res["events_sent"],
        "frames": res["frames"],
        "batch": args.batch,
        "admission": res["admission"],
        "wall_s": res["wall_s"],
        "events_per_sec": res["events_per_sec"],
        "server_stats": {k: res["server_stats"][k]
                         for k in ("ticks", "incorporated", "version")},
    }
    print(f"[serving_bench] socket: {res['events_sent']} events in "
          f"{res['frames']} frames, {res['events_per_sec']:.0f} events/s "
          f"through the wire", file=sys.stderr)
    return [row]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--users", type=int, default=1_000_000,
                    help="simulated user population (default 1M)")
    ap.add_argument("--arrivals", type=int, default=1_000_000,
                    help="arrival events in the trace (default 1M)")
    ap.add_argument("--horizon", type=float, default=60.0,
                    help="virtual-time horizon in seconds")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cohort", type=int, default=8)
    ap.add_argument("--buffer-size", type=int, default=4)
    ap.add_argument("--tick-interval", type=float, default=0.05,
                    help="virtual seconds per engine tick (default 0.05 "
                         "=> horizon/0.05 ticks regardless of arrival "
                         "count)")
    ap.add_argument("--flush-every", type=int, default=0)
    ap.add_argument("--rate-limit", type=float, default=0.0)
    ap.add_argument("--max-pending", type=int, default=0)
    ap.add_argument("--socket-events", type=int, default=20_000,
                    help="bounded event count for the socket row "
                         "(default 20k)")
    ap.add_argument("--batch", type=int, default=2048,
                    help="loadgen events per protocol frame")
    ap.add_argument("--skip-socket", action="store_true",
                    help="only the in-process row")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    rows = bench_inproc(args)
    if not args.skip_socket:
        rows += bench_socket(args)
    out = open(args.json, "w") if args.json else None
    for r in rows:
        line = json.dumps(r, default=float)
        print(line)
        if out:
            out.write(line + "\n")
    if out:
        out.close()


if __name__ == "__main__":
    main()
