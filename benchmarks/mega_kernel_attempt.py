"""The mega-kernel ATTEMPT: one Pallas kernel per federated round — a
preserved NEGATIVE result (benchmarks/RESULTS.md 'Roofline', round 4).

The whole round — per-client train fwd+bwd+Adam, eval confusion matrix,
and the weighted-average accumulation — runs in a single pallas_call
with activations never leaving VMEM. It is numerically right (asserts
below: one-round parity vs the production XLA round at matmul-precision
level, and trajectory agreement at round 100), and it is ~3x SLOWER
than the XLA round on the v5e (~62 us vs ~22 us marginal): Mosaic's
matmul codegen for these pad-dominated shapes (K=14, N=2 against the
128-lane MXU) loses far more than fusing the activation streams saves.
Stage bisect: the forward alone costs 18.7 us in-kernel vs the entire
XLA round's 21.5 us.

Kept runnable so the conclusion stays reproducible; do not wire into
the production path. Run: ``python benchmarks/mega_kernel_attempt.py``
(~2 min on the v5e; requires the TPU backend for the timing part).
"""
import sys
sys.path.insert(0, __file__.rsplit("/", 2)[0])

import time, functools, numpy as np, jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from fedtpu.config import DataConfig, ModelConfig, OptimConfig, ShardConfig, default_income_csv
from fedtpu.data.tabular import load_tabular_dataset
from fedtpu.data.sharding import pack_clients
from fedtpu.models import build_model
from fedtpu.ops import build_optimizer
from fedtpu.parallel import make_mesh
from fedtpu.parallel.round import build_round_fn, init_federated_state
from fedtpu.utils.trees import clone
from fedtpu.utils.timing import force_fetch, marginal_slope

ds = load_tabular_dataset(DataConfig(csv_path=default_income_csv()))
packed = pack_clients(ds.x_train, ds.y_train, ShardConfig(num_clients=8))
xd = jnp.asarray(packed.x); yd = jnp.asarray(packed.y).astype(jnp.int32); md = jnp.asarray(packed.mask)
C, N, D = xd.shape
K = 2
dims = [D, 50, 200, K]
NL = 3
B1, B2, EPS = 0.9, 0.999, 1e-8
LR0, GAMMA, STEPSZ = 0.004, 0.5, 30

ohm = (jax.nn.one_hot(yd, K, dtype=jnp.float32) * md[..., None])   # (C,N,K) masked one-hot
mask3 = md[..., None]                                               # (C,N,1)

def kernel(scalars_ref, wn_ref, den_ref, x_ref, ohm_ref, m_ref, *refs):
    c = pl.program_id(0)
    lr = scalars_ref[0]; c1 = scalars_ref[1]; c2 = scalars_ref[2]
    wn = wn_ref[c]; denom = den_ref[c]
    iw = lambda i: refs[3*i][0]
    imw = lambda i: refs[3*i+1][0]
    inw = lambda i: refs[3*i+2][0]
    ib = lambda i: refs[3*NL + 3*i][pl.ds(c, 1), :]
    imb = lambda i: refs[3*NL + 3*i+1][pl.ds(c, 1), :]
    inb = lambda i: refs[3*NL + 3*i+2][pl.ds(c, 1), :]
    o = 6*NL
    out_aggW = lambda i: refs[o + i]
    out_aggB = lambda i: refs[o + NL + i]
    out_muw = lambda i: refs[o + 2*NL + i]
    out_nuw = lambda i: refs[o + 3*NL + i]
    out_mub = lambda i: refs[o + 4*NL + i]
    out_nub = lambda i: refs[o + 5*NL + i]
    out_loss = refs[o + 6*NL]
    out_conf = refs[o + 6*NL + 1]

    x = x_ref[0]          # (N, D)
    oh = ohm_ref[0]       # (N, K) masked one-hot
    msk = m_ref[0]        # (N, 1)
    hs = [x]
    h = x
    for i in range(NL):
        z = jnp.dot(h, iw(i), preferred_element_type=jnp.float32) + ib(i)
        h = jnp.maximum(z, 0.0) if i < NL - 1 else z
        hs.append(h)
    logits = hs[-1]
    zmax = jnp.max(logits, axis=-1, keepdims=True)
    ls = logits - zmax
    lse = jnp.log(jnp.sum(jnp.exp(ls), axis=-1, keepdims=True))
    logp = ls - lse
    loss = -jnp.sum(logp * oh) / denom
    out_loss[pl.ds(c, 1), :] = jnp.full((1, 128), loss, jnp.float32)
    p = jnp.exp(logp)
    dz = (p * msk - oh) / denom
    gW, gB = [None]*NL, [None]*NL
    for i in range(NL - 1, -1, -1):
        a = hs[i]
        gW[i] = jax.lax.dot_general(a, dz, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
        gB[i] = jnp.sum(dz, axis=0, keepdims=True)
        if i > 0:
            dh = jax.lax.dot_general(dz, iw(i), (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dz = dh * (hs[i] > 0.0).astype(jnp.float32)
    trainedW, trainedB = [None]*NL, [None]*NL
    for i in range(NL):
        for (g, pv, mu, nu, st_mu, st_nu, is_w) in (
                (gW[i], iw(i), imw(i), inw(i), out_muw(i), out_nuw(i), True),
                (gB[i], ib(i), imb(i), inb(i), out_mub(i), out_nub(i), False)):
            mu2 = B1 * mu + (1 - B1) * g
            nu2 = B2 * nu + (1 - B2) * g * g
            newp = pv - lr * (mu2 / c1) / (jnp.sqrt(nu2 / c2) + EPS)
            if is_w:
                st_mu[0] = mu2
                st_nu[0] = nu2
                trainedW[i] = newp
                @pl.when(c == 0)
                def _():
                    out_aggW(i)[...] = jnp.zeros_like(out_aggW(i))
                out_aggW(i)[...] += wn * newp
            else:
                st_mu[pl.ds(c, 1), :] = mu2
                st_nu[pl.ds(c, 1), :] = nu2
                trainedB[i] = newp
                @pl.when(c == 0)
                def _():
                    out_aggB(i)[...] = jnp.zeros_like(out_aggB(i))
                out_aggB(i)[pl.ds(0, 1), :] += wn * newp
    h = x
    for i in range(NL):
        z = jnp.dot(h, trainedW[i], preferred_element_type=jnp.float32) + trainedB[i]
        h = jnp.maximum(z, 0.0) if i < NL - 1 else z
    best = h[:, 0:1]
    idx = jnp.zeros((N, 1), jnp.float32)
    for k in range(1, K):
        cur = h[:, k:k+1]
        better = cur > best
        idx = jnp.where(better, jnp.float32(k), idx)
        best = jnp.maximum(best, cur)
    pred_oh = jnp.concatenate([(idx == jnp.float32(k)).astype(jnp.float32)
                               for k in range(K)], axis=1)      # (N, K)
    conf = jax.lax.dot_general(oh, pred_oh, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.HIGHEST)
    out_conf[0] = jnp.pad(conf, ((0, 8-K), (0, 128-K)))

def fused_round(flat, scalars, wn_arr, den_arr):
    Ws, Bs, muW, nuW, muB, nuB = flat
    args = [scalars, wn_arr, den_arr, xd, ohm, mask3]
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, N, D), lambda c: (c, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, N, K), lambda c: (c, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, N, 1), lambda c: (c, 0, 0), memory_space=pltpu.VMEM)]
    for i in range(NL):
        for t in (Ws[i], muW[i], nuW[i]):
            args.append(t)
            in_specs.append(pl.BlockSpec((1, dims[i], dims[i+1]), lambda c: (c, 0, 0), memory_space=pltpu.VMEM))
    for i in range(NL):
        for t in (Bs[i], muB[i], nuB[i]):
            args.append(t)
            in_specs.append(pl.BlockSpec((C, dims[i+1]), lambda c: (0, 0), memory_space=pltpu.VMEM))
    out_shapes, out_specs = [], []
    for i in range(NL):
        out_shapes.append(jax.ShapeDtypeStruct((dims[i], dims[i+1]), jnp.float32))
        out_specs.append(pl.BlockSpec((dims[i], dims[i+1]), lambda c: (0, 0), memory_space=pltpu.VMEM))
    for i in range(NL):
        out_shapes.append(jax.ShapeDtypeStruct((8, dims[i+1]), jnp.float32))
        out_specs.append(pl.BlockSpec((8, dims[i+1]), lambda c: (0, 0), memory_space=pltpu.VMEM))
    for _ in range(2):
        for i in range(NL):
            out_shapes.append(jax.ShapeDtypeStruct((C, dims[i], dims[i+1]), jnp.float32))
            out_specs.append(pl.BlockSpec((1, dims[i], dims[i+1]), lambda c: (c, 0, 0), memory_space=pltpu.VMEM))
    for _ in range(2):
        for i in range(NL):
            out_shapes.append(jax.ShapeDtypeStruct((C, dims[i+1]), jnp.float32))
            out_specs.append(pl.BlockSpec((C, dims[i+1]), lambda c: (0, 0), memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((C, 128), jnp.float32))
    out_specs.append(pl.BlockSpec((C, 128), lambda c: (0, 0), memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((C, 8, 128), jnp.float32))
    out_specs.append(pl.BlockSpec((1, 8, 128), lambda c: (c, 0, 0), memory_space=pltpu.VMEM))
    outs = pl.pallas_call(kernel, grid=(C,), in_specs=in_specs,
                          out_specs=out_specs, out_shape=out_shapes)(*args)
    aggW = outs[:NL]
    aggB = [outs[NL+i][0] for i in range(NL)]
    muW2 = outs[2*NL:3*NL]; nuW2 = outs[3*NL:4*NL]
    muB2 = outs[4*NL:5*NL]; nuB2 = outs[5*NL:6*NL]
    loss = outs[6*NL][:, 0]
    conf = outs[6*NL+1][:, :K, :K]
    return aggW, aggB, muW2, nuW2, muB2, nuB2, loss, conf

mesh = make_mesh(num_clients=8)
init_fn, apply_fn = build_model(ModelConfig(input_dim=D, num_classes=K))
tx = build_optimizer(OptimConfig())
state0 = init_federated_state(jax.random.key(0), mesh, 8, init_fn, tx)
xla_step = build_round_fn(mesh, apply_fn, tx, K, rounds_per_step=1)
batch = {"x": jax.device_put(packed.x), "y": jax.device_put(packed.y), "mask": jax.device_put(packed.mask)}
s_x, m_x = xla_step(clone(state0), batch)

def unpack(state):
    layers = state["params"]["layers"]
    Ws = [l["w"] for l in layers]; Bs = [l["b"] for l in layers]
    adam = state["opt_state"][0]
    muW = [l["w"] for l in adam.mu["layers"]]; nuW = [l["w"] for l in adam.nu["layers"]]
    muB = [l["b"] for l in adam.mu["layers"]]; nuB = [l["b"] for l in adam.nu["layers"]]
    return [Ws, Bs, muW, nuW, muB, nuB]

flat = unpack(clone(state0))
t = 0
lr = LR0 * (GAMMA ** (t // STEPSZ))
c1 = 1 - B1 ** (t + 1); c2 = 1 - B2 ** (t + 1)
scalars = jnp.asarray([lr, c1, c2], jnp.float32)
w = md.sum(axis=1)
wn_arr = (w / w.sum()).astype(jnp.float32)
den_arr = jnp.maximum(w, 1.0).astype(jnp.float32)
aggW, aggB, muW2, nuW2, muB2, nuB2, loss, conf = jax.jit(fused_round)(flat, scalars, wn_arr, den_arr)

for i in range(NL):
    gw_x = np.asarray(s_x["params"]["layers"][i]["w"])[0]
    gb_x = np.asarray(s_x["params"]["layers"][i]["b"])[0]
    dw = np.abs(np.asarray(aggW[i]) - gw_x).max()
    db = np.abs(np.asarray(aggB[i]) - gb_x).max()
    print(f"layer {i}: dW {dw:.2e}  dB {db:.2e}")
    # matmul-precision level (Adam's sign-sensitive rescaling at t=0
    # amplifies bf16-pass matmul differences; 2*lr = 8e-3 is the cap)
    assert dw < 8e-3 and db < 8e-3, "mega-kernel diverged from XLA round"
ld = np.abs(np.asarray(loss) - np.asarray(m_x["loss"]).ravel()).max()
print("loss diff:", ld)
assert ld < 1e-5
pc = np.asarray(m_x["per_client"]["accuracy"])
acc_pal = np.asarray(conf[:, 0, 0] + conf[:, 1, 1]) / np.asarray(conf.sum((1, 2)))
print("acc diff:", np.abs(acc_pal - pc).max())

# ---- scan R rounds with the fused kernel; trajectory + marginal timing
def make_scan(R):
    @jax.jit
    def f(flat):
        def body(carry, r):
            Ws, Bs, muW, nuW, muB, nuB = carry
            t = r
            lr_t = LR0 * jnp.power(GAMMA, (t // STEPSZ).astype(jnp.float32))
            c1_t = 1 - jnp.power(B1, (t + 1).astype(jnp.float32))
            c2_t = 1 - jnp.power(B2, (t + 1).astype(jnp.float32))
            sc = jnp.stack([lr_t, c1_t, c2_t]).astype(jnp.float32)
            aggW, aggB, muW2, nuW2, muB2, nuB2, loss, conf = fused_round(
                [Ws, Bs, muW, nuW, muB, nuB], sc, wn_arr, den_arr)
            WsN = [jnp.broadcast_to(aggW[i][None], Ws[i].shape) for i in range(NL)]
            BsN = [jnp.broadcast_to(aggB[i][None], Bs[i].shape) for i in range(NL)]
            return [list(WsN), list(BsN), list(muW2), list(nuW2), list(muB2), list(nuB2)], (loss, conf)
        carry, (losses, confs) = jax.lax.scan(body, flat, jnp.arange(R))
        return carry, losses, confs
    return f

f100 = make_scan(100)
carry, losses, confs = f100(unpack(clone(state0)))
acc = np.asarray(confs[-1, :, 0, 0] + confs[-1, :, 1, 1]) / np.asarray(confs[-1].sum((1, 2)))

# XLA reference: 100 rounds
xla100 = build_round_fn(mesh, apply_fn, tx, K, rounds_per_step=100)
s_x2, m_x2 = xla100(clone(state0), batch)
acc_x = np.asarray(m_x2["per_client"]["accuracy"])[-1]
print("acc after 100 rounds: fused", acc.mean(), "xla", acc_x.mean())
assert abs(acc.mean() - acc_x.mean()) < 0.01, "trajectory diverged"


flat0 = unpack(clone(state0))
def mk(R):
    f = make_scan(R)
    def run():
        carry, losses, confs = f(flat0)
        return confs[-1].sum()
    return run
m = marginal_slope(mk)
flops = 736897920.0
print(f"fused round marginal: {m*1e6:.2f} us/round -> {flops/m/1e12:.1f} TFLOP/s, {flops/m/158e12*100:.1f}% MFU vs measured peak")
