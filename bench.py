"""Headline benchmark: sec/round of 8-client weighted FedAvg on the income MLP.

Prints ONE JSON line:
    {"metric": "sec_per_round_fedavg8_income_mlp", "value": <ours>,
     "unit": "s", "vs_baseline": <baseline/ours speedup>}

Ours: the fedtpu compiled round (local full-batch Adam step + in-graph
weighted FedAvg + in-graph metrics) on the default JAX backend (the TPU chip
when present), one ('clients',) mesh over the visible devices, 8 clients.

Baseline: the reference publishes no numbers (BASELINE.md), so the baseline is
MEASURED here as a faithful single-host simulation of the reference's per-round
work under ``mpirun -np 8`` (FL_CustomMLP...:63-120): per rank a full-batch
torch forward/backward/Adam step + argmax eval on its shard, then the rank-0
aggregation path — pickle every rank's weight dict (comm.gather), numpy
weighted average, pickle the global dict back out (comm.bcast), and load into
each model. Ranks run concurrently under mpirun, so the compute part is
divided by min(8, cpu_count) (ideal oversubscription); the serialization +
averaging path is inherently serialized through rank 0 and is not divided.
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import time

import numpy as np

ROUNDS = 100
WARMUP = 3
NUM_CLIENTS = 8
# Rounds scanned per compiled program (the production throughput knob,
# RunConfig.rounds_per_step). Dispatch overhead amortizes with the scan
# depth: ~13 us/round at 10, ~1.1 us/round at 100 (v5e, income MLP).
ROUNDS_PER_STEP = 100


def _dataset():
    from fedtpu.config import DataConfig, default_income_csv

    from fedtpu.data.tabular import load_tabular_dataset

    csv = default_income_csv()
    return load_tabular_dataset(DataConfig(csv_path=csv))


def bench_fedtpu(ds) -> dict:
    import jax

    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh, client_sharding
    from fedtpu.parallel.round import build_round_fn, init_federated_state

    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {
        "x": jax.device_put(packed.x, shard),
        "y": jax.device_put(packed.y, shard),
        "mask": jax.device_put(packed.mask, shard),
    }
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                 init_fn, tx)
    round_step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                                rounds_per_step=ROUNDS_PER_STEP)

    for _ in range(WARMUP):
        state, metrics = round_step(state, batch)
    jax.block_until_ready(state["params"])

    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        state, metrics = round_step(state, batch)
    jax.block_until_ready(state["params"])
    sec_per_round = (time.perf_counter() - t0) / (ROUNDS * ROUNDS_PER_STEP)
    return {"sec_per_round": sec_per_round,
            "rounds_per_step": ROUNDS_PER_STEP,
            "accuracy": float(np.atleast_1d(
                np.asarray(metrics["client_mean"]["accuracy"]))[-1]),
            "devices": len(mesh.devices.ravel()),
            "backend": mesh.devices.ravel()[0].platform}


def bench_reference_equivalent(ds) -> dict:
    """Measured reference-equivalent baseline; see module docstring."""
    import torch
    import torch.nn as nn

    def make_model():
        # Same architecture as FL_CustomMLP...:12-25, hidden [50, 200] (:40).
        return nn.Sequential(
            nn.Linear(ds.input_dim, 50), nn.ReLU(),
            nn.Linear(50, 200), nn.ReLU(),
            nn.Linear(200, ds.num_classes))

    torch.set_num_threads(max(1, os.cpu_count() or 1))
    n = len(ds.x_train)
    chunk = max(1, n // NUM_CLIENTS)
    shards = []
    for r in range(NUM_CLIENTS):
        s, e = r * chunk, (r + 1) * chunk if r != NUM_CLIENTS - 1 else n
        shards.append((torch.tensor(ds.x_train[s:e]),
                       torch.tensor(ds.y_train[s:e], dtype=torch.long)))

    models = [make_model() for _ in range(NUM_CLIENTS)]
    opts = [torch.optim.Adam(m.parameters(), lr=0.004) for m in models]
    scheds = [torch.optim.lr_scheduler.StepLR(o, step_size=30, gamma=0.5)
              for o in opts]
    crit = nn.CrossEntropyLoss()

    def one_round():
        t_compute = 0.0
        t_serial = 0.0
        gathered = []
        sizes = []
        for m, o, sch, (x, y) in zip(models, opts, scheds, shards):
            t0 = time.perf_counter()
            # train_one_epoch (:63-73): one full-batch fwd/bwd/Adam step.
            o.zero_grad()
            loss = crit(m(x), y)
            loss.backward()
            o.step()
            sch.step()
            # evaluate_local (:75-91): argmax on the local shard.
            with torch.no_grad():
                m(x).argmax(dim=1).numpy()
            t_compute += time.perf_counter() - t0

            t0 = time.perf_counter()
            # get_weights + comm.gather pickling (:93-94,105).
            w = {k: v.detach().numpy().copy()
                 for k, v in m.named_parameters()}
            gathered.append(pickle.loads(pickle.dumps(w)))
            sizes.append(len(x))
            t_serial += time.perf_counter() - t0

        t0 = time.perf_counter()
        # rank-0 weighted average (:108-116).
        total = sum(sizes)
        avg = {k: sum(g[k] * (s / total) for g, s in zip(gathered, sizes))
               for k in gathered[0]}
        # comm.bcast back out + set_weights (:119-120).
        for m in models:
            blob = pickle.loads(pickle.dumps(avg))
            with torch.no_grad():
                for k, p in m.named_parameters():
                    p.copy_(torch.tensor(blob[k]))
        t_serial += time.perf_counter() - t0
        return t_compute, t_serial

    one_round()  # warmup
    reps = 5
    tc, ts = 0.0, 0.0
    for _ in range(reps):
        a, b = one_round()
        tc += a
        ts += b
    tc, ts = tc / reps, ts / reps
    # mpirun runs ranks concurrently: ideal-parallel compute, serial comm.
    parallel = min(NUM_CLIENTS, os.cpu_count() or 1)
    return {"sec_per_round": tc / parallel + ts,
            "compute_s": tc, "serial_s": ts, "assumed_parallelism": parallel}


def main():
    ds = _dataset()
    ours = bench_fedtpu(ds)
    base = bench_reference_equivalent(ds)
    result = {
        "metric": "sec_per_round_fedavg8_income_mlp",
        # 3 significant figures, not fixed decimals — the value sits at
        # microsecond scale where round(v, 6) would destroy it.
        "value": float(f"{ours['sec_per_round']:.3g}"),
        "unit": "s",
        "vs_baseline": float(
            f"{base['sec_per_round'] / ours['sec_per_round']:.4g}"),
    }
    print(json.dumps(result))
    # Detail lines on stderr so stdout stays one JSON line.
    print(f"[bench] ours: {ours}", file=sys.stderr)
    print(f"[bench] baseline(measured reference-equivalent): {base}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
