"""Headline benchmark: sec/round of 8-client weighted FedAvg on the income MLP.

Prints ONE JSON line:
    {"metric": "sec_per_round_fedavg8_income_mlp", "value": <ours>,
     "unit": "s", "vs_baseline": <baseline/ours speedup>}

Ours: the fedtpu compiled round (local full-batch Adam step + in-graph
weighted FedAvg + in-graph metrics) on the default JAX backend (the TPU chip
when present), one ('clients',) mesh over the visible devices, 8 clients.
The headline value is measured at rounds_per_step=100 (the production
throughput knob: 100 rounds scanned per compiled program, early-stop checks
at chunk boundaries); the full rps sweep is reported on stderr.

TIMING METHODOLOGY (round-2 rewrite — the round-1 numbers were wrong):
``jax.block_until_ready`` does NOT synchronize on this platform's remote
('axon') transport — closing a timed window with it measures dispatch rate,
not compute, which overstated round 1's speedup ~500x (22,260x recorded;
~44x real). Every timed window here is closed by ``force_fetch`` (a host
value fetch that provably depends on the full program), and every result
must pass ``assert_above_flops_floor``: sec/round >= program FLOPs /
(2 x measured device peak), with peak measured on-device by a
dispatch-cancelling matmul-chain slope. A floor violation crashes the
benchmark rather than recording a fantasy number.

The ``mpmd_sync`` row reruns the synchronous early-stopping loop shape
through the ``--mpmd`` DAG (PR 18, ``fedtpu/orchestration/mpmd.py``)
with bitwise metric-history parity re-proven in-run; see
``bench_mpmd_sync``.

Baseline: the reference publishes no numbers (BASELINE.md), so the baseline
is MEASURED here as a faithful single-host simulation of the reference's
per-round work under ``mpirun -np 8`` (FL_CustomMLP...:63-120): per rank a
full-batch torch forward/backward/Adam step + argmax eval on its shard, then
the rank-0 aggregation path — pickle every rank's weight dict (comm.gather),
numpy weighted average, pickle the global dict back out (comm.bcast), and
load into each model. Ranks run concurrently under mpirun, so the compute
part is divided by min(8, cpu_count) (ideal oversubscription); the
serialization + averaging path is inherently serialized through rank 0 and
is not divided.
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time

import numpy as np

NUM_CLIENTS = 8
# rounds_per_step values swept; the headline is HEADLINE_RPS. Dispatch
# overhead (~60-100 ms/call through the tunnel) amortizes with scan depth,
# so sec/round falls steeply with rps and flattens toward the ~22 us/round
# marginal on-chip cost. rps=4000 is the recorded throughput ceiling
# (~3.0e-5 s/round — still dispatch-shared; the headline stays at the
# production knob rps=100, where early-stop checks remain round-granular
# enough for the reference's patience-10 driver).
RPS_SWEEP = (1, 10, 100, 1000, 4000)
HEADLINE_RPS = 100


def _dataset():
    from fedtpu.config import DataConfig, default_income_csv

    from fedtpu.data.tabular import load_tabular_dataset

    csv = default_income_csv()
    return load_tabular_dataset(DataConfig(csv_path=csv))


def bench_fedtpu(ds) -> dict:
    import jax

    from fedtpu.config import ModelConfig, OptimConfig, ShardConfig
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh, client_sharding
    from fedtpu.parallel.round import build_round_fn, init_federated_state
    from fedtpu.utils.timing import (assert_above_flops_floor,
                                     compile_with_flops, force_fetch,
                                     measured_peak_flops, timed_rounds)

    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {
        "x": jax.device_put(packed.x, shard),
        "y": jax.device_put(packed.y, shard),
        "mask": jax.device_put(packed.mask, shard),
    }
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())

    # Device peak for the flops floor, measured at the matmul rate the model
    # actually gets (XLA default precision; on TPU f32 matmuls ride the MXU
    # in bf16 passes, so this sits near the bf16 spec peak — a HIGH peak
    # only loosens the floor, which is the safe direction).
    dev = mesh.devices.ravel()[0]
    peak = measured_peak_flops(dtype="float32", device=dev)

    # Any backend compile inside a timed window is an unexpected retrace:
    # each rps's program compiles in compile_with_flops BEFORE arming, so
    # the armed count must stay 0 (BENCH_* files regress on it).
    from fedtpu.analysis.guards import RecompileSentinel
    sentinel = RecompileSentinel(label="bench_timed_windows")

    sweep = {}
    flops_per_round = None
    cold_compile_s = None
    warm_lookup_ms = None
    for rps in RPS_SWEEP:
        state = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                     init_fn, tx)
        step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                              rounds_per_step=rps)
        # compile_with_flops raises if XLA cost analysis is unavailable —
        # no floor, no number. A lax.scan body is counted ONCE regardless
        # of length, so the scanned program's "flops" IS the per-round cost
        # (verified: cost(rps=100) == cost(rps=1) on this backend).
        t_compile = time.perf_counter()
        step, flops_per_round = compile_with_flops(step, state, batch)
        if rps == HEADLINE_RPS:
            # Compile-cost companion numbers for the headline program: what
            # a cold start pays (trace+XLA compile) vs what a warm
            # --compilation-cache start pays instead (serialized-executable
            # round-trip through fedtpu.compilation.ProgramCache).
            cold_compile_s = time.perf_counter() - t_compile
            warm_lookup_ms = _warm_lookup_ms(step)

        # PIPELINED throughput: back-to-back calls, one completion-proving
        # fetch at the end (the fixed-rounds production shape — run N
        # chunks, read results at the end). Dispatch overlaps compute.
        # timed_rounds is the mandatory harness: fetch-forced window +
        # flops-floor check. Multiple independent windows per rps: dispatch
        # jitter on the tunneled transport is ~±15%, and recording a single
        # window lets the artifact quote the top of its own jitter band
        # (review r2) — report the median and keep the band. The headline
        # gets 5 windows; every other row gets 2, so no row ever records a
        # degenerate zero-width band (advisor r3).
        n_calls = max(3, min(20, 2000 // rps))
        reps = 5 if rps == HEADLINE_RPS else 2
        samples = []
        with sentinel.armed():
            for _ in range(reps):
                sec_rep, state, metrics = timed_rounds(
                    step, state, batch, n_calls, rps, peak, flops_per_round,
                    label=f"rps={rps}")
                samples.append(sec_rep)
        sec_per_round = float(np.median(samples))
        acc = float(np.asarray(metrics["client_mean"]["accuracy"]).ravel()[-1])
        # The rounds the accuracy is attributed to must count EVERYTHING
        # the state trained through — warmup calls and all timed windows
        # across all reps — not just one window's n_calls * rps. The
        # state's own round counter is the exact ledger.
        rounds_trained = int(np.asarray(state["round"]))

        # SYNCHRONOUS latency: fetch the metrics after every call — the
        # early-stopping production loop's shape (host inspects metrics at
        # each chunk boundary), paying one dispatch+fetch RTT per chunk.
        t0 = time.perf_counter()
        sync_calls = 3
        with sentinel.armed():
            for _ in range(sync_calls):
                state, metrics = step(state, batch)
                force_fetch(metrics["client_mean"]["accuracy"])
        sec_sync = (time.perf_counter() - t0) / (sync_calls * rps)

        floor = assert_above_flops_floor(sec_per_round, flops_per_round,
                                         peak, label=f"rps={rps}")
        assert_above_flops_floor(sec_sync, flops_per_round, peak,
                                 label=f"rps={rps} sync")
        sweep[rps] = {"sec_per_round": sec_per_round,
                      "sec_per_round_range": [float(min(samples)),
                                              float(max(samples))],
                      "sec_per_round_sync": sec_sync,
                      "rounds_timed": n_calls * rps,
                      "rounds_trained": rounds_trained,
                      "floor_sec": floor,
                      # Model FLOPs utilization at this rps: fraction of the
                      # measured device peak the timed program sustains.
                      "mfu": flops_per_round / (sec_per_round * peak),
                      "final_accuracy": acc}

    head = sweep[HEADLINE_RPS]
    # Training must be real: ~2000+ rounds on the income MLP reaches ~0.83
    # accuracy (round-1 verified trajectory). A dead program would fail here.
    if head["final_accuracy"] < 0.75:
        raise RuntimeError(
            f"benchmark program is not actually training: accuracy "
            f"{head['final_accuracy']:.3f} after {head['rounds_trained']} "
            "rounds (expected ~0.83)")
    return {"sec_per_round": head["sec_per_round"],
            "sec_per_round_range": head["sec_per_round_range"],
            "sec_per_round_sync": head["sec_per_round_sync"],
            "rounds_per_step": HEADLINE_RPS,
            "accuracy": head["final_accuracy"],
            "devices": len(mesh.devices.ravel()),
            "backend": dev.platform,
            "peak_flops_measured": peak,
            "flops_per_round": flops_per_round,
            "mfu": head["mfu"],
            "recompiles": sentinel.count,
            "cold_compile_s": cold_compile_s,
            "warm_lookup_ms": warm_lookup_ms,
            "sweep": sweep}


def _warm_lookup_ms(compiled):
    """Serialized-executable round-trip for the headline program: store to
    a scratch ProgramCache, then time a FRESH cache instance's load — the
    startup cost a warm ``--compilation-cache`` run pays in place of
    cold_compile_s (benchmarks/compile_bench.py asserts the ratio)."""
    import tempfile

    from fedtpu.compilation import ProgramCache
    with tempfile.TemporaryDirectory() as d:
        if not ProgramCache(d).store("bench-headline", compiled):
            return None                 # serialization unsupported here
        entry = ProgramCache(d).load("bench-headline")
        return entry.seconds * 1e3 if entry is not None else None


def bench_mfu_capability(peak: float) -> dict:
    """The >=50% MFU capability point, machine-captured (VERDICT r4 #4).

    The income headline above is BYTE-bound at ~22% marginal MFU — that is
    its bandwidth roofline, proven in benchmarks/roofline.py and RESULTS.md.
    This row runs the IDENTICAL round program at an MXU-sized shape
    (hidden [512, 512], 800 rows/client, synthetic income-like data) so the
    artifact itself carries the engine's compute capability, not just the
    workload's bandwidth ceiling. Measured as a scan-length SLOPE
    (per-round marginal between rps=200 and rps=800 windows, fetch-forced)
    so the ~100 ms tunneled dispatch RTT cancels exactly — the same
    methodology as measured_peak_flops and benchmarks/roofline.py; the
    flops floor still applies."""
    import time as _time

    import jax

    from fedtpu.config import (DataConfig, ModelConfig, OptimConfig,
                               ShardConfig)
    from fedtpu.data import load_dataset
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.parallel import make_mesh, client_sharding
    from fedtpu.parallel.round import build_round_fn, init_federated_state
    from fedtpu.utils.timing import (assert_above_flops_floor,
                                     compile_with_flops, force_fetch)
    from fedtpu.utils.trees import clone

    HIDDEN, ROWS = (512, 512), 800
    ds = load_dataset(DataConfig(csv_path=None,
                                 synthetic_rows=ROWS * NUM_CLIENTS,
                                 synthetic_features=14))
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(
        ModelConfig(input_dim=ds.input_dim, hidden_sizes=HIDDEN,
                    num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    state = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                 init_fn, tx)

    n_calls = 5
    times = {}
    flops = None
    for rps in (200, 800):
        step = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                              rounds_per_step=rps)
        step, flops = compile_with_flops(step, clone(state), batch)
        s = clone(state)
        s, m = step(s, batch)                     # warmup this executable
        force_fetch(m)
        best = float("inf")
        for _ in range(3):
            s = clone(state)
            t0 = _time.perf_counter()
            for _ in range(n_calls):
                s, m = step(s, batch)
            force_fetch(m)
            best = min(best, _time.perf_counter() - t0)
        times[rps] = best
    marginal = (times[800] - times[200]) / (n_calls * (800 - 200))
    assert_above_flops_floor(marginal, flops, peak, label="mfu capability")
    return {"hidden": list(HIDDEN), "rows_per_client": ROWS,
            "marginal_s_per_round": marginal, "flops_per_round": flops,
            "peak_flops_measured": peak,
            "mfu": flops / (marginal * peak)}


# BENCH_r05's recorded rps=100 operating point on the tunneled TPU
# transport: pipelined 7.088e-5 s/round — i.e. 7.088e-3 s of overlapped
# dispatch+compute per 100-round chunk — against synchronous 1.039e-3
# s/round. The 9.68e-2 s/chunk difference is the serialized
# dispatch+fetch RTT the sync loop pays per chunk and the pipelined
# loop hides; it is the input to the clearly-labeled schedule model in
# bench_mpmd_sync (the measured improvement is reported alongside it).
TUNNEL_CHUNK_COMPUTE_S = 7.088e-5 * HEADLINE_RPS
TUNNEL_RTT_S = (1.039e-3 - 7.088e-5) * HEADLINE_RPS


def bench_mpmd_sync(ds, peak: float) -> dict:
    """Sync-mode MPMD row: the early-stopping loop shape rerun through
    the ``--mpmd`` DAG (fedtpu/orchestration/mpmd.py).

    The monolithic sync loop blocks on a metric fetch after every chunk
    — dispatch + compute + fetch serialized per chunk, the 15x gap the
    sweep's sync column records on the tunneled transport. The MPMD loop
    is the production ``RunConfig.mpmd`` schedule: the whole DAG is
    enqueued async (client chain on the round mesh, the metrics
    program's tiny output pushed eagerly to the server submesh) and the
    early-stop decision lags one in-flight chunk, so chunk k's fetch
    drains under chunk k+1's compute and the RTT leaves the critical
    path.

    Parity is load-bearing and CRASHES on failure: the two loops'
    fetched metric histories and final states must be bitwise equal —
    the tests/test_mpmd.py oracle contract, re-proven inside the
    artifact every run.

    Two improvement numbers ride in the row. ``improvement_measured``
    is real on THIS backend's transport: on the tunneled TPU transport
    the hidden RTT is ~0.1 s/chunk and the ratio lands near the sync/
    pipelined split; on a local CPU backend the RTT is ~0 and the ratio
    is honestly ~1. ``improvement_modeled_tunnel`` is a deterministic
    schedule model at BENCH_r05's recorded rps=100 tunnel operating
    point (constants above): the lag-1 pending schedule takes the
    per-chunk RTT off the critical path, so the improvement is
    (chunk_compute + rtt) / chunk_compute — a model, labeled as such,
    with its inputs in the row.
    """
    import jax

    from fedtpu.analysis.guards import RecompileSentinel
    from fedtpu.config import (ExperimentConfig, ModelConfig, OptimConfig,
                               RunConfig, ShardConfig)
    from fedtpu.data.sharding import pack_clients
    from fedtpu.models import build_model
    from fedtpu.ops import build_optimizer
    from fedtpu.orchestration.mpmd import build_mpmd_step
    from fedtpu.parallel import make_mesh, client_sharding
    from fedtpu.parallel.round import build_round_fn, init_federated_state
    from fedtpu.utils.timing import (assert_above_flops_floor,
                                     compile_with_flops, force_fetch)
    from fedtpu.utils.trees import clone

    rps = HEADLINE_RPS
    mesh = make_mesh(num_clients=NUM_CLIENTS)
    shard = client_sharding(mesh)
    packed = pack_clients(ds.x_train, ds.y_train,
                          ShardConfig(num_clients=NUM_CLIENTS))
    batch = {k: jax.device_put(v, shard) for k, v in
             {"x": packed.x, "y": packed.y, "mask": packed.mask}.items()}
    init_fn, apply_fn = build_model(ModelConfig(input_dim=ds.input_dim,
                                                num_classes=ds.num_classes))
    tx = build_optimizer(OptimConfig())
    state0 = init_federated_state(jax.random.key(0), mesh, NUM_CLIENTS,
                                  init_fn, tx)

    mono = build_round_fn(mesh, apply_fn, tx, ds.num_classes,
                          rounds_per_step=rps)
    mono, flops = compile_with_flops(mono, clone(state0), batch)
    cfg = ExperimentConfig(
        model=ModelConfig(input_dim=ds.input_dim,
                          num_classes=ds.num_classes),
        shard=ShardConfig(num_clients=NUM_CLIENTS),
        run=RunConfig(mpmd=True, rounds_per_step=rps))
    mpmd = build_mpmd_step(cfg, mesh=mesh, apply_fn=apply_fn, tx=tx,
                           num_classes=ds.num_classes, state=state0,
                           batch=batch, width=rps)

    chunks = 6
    sentinel = RecompileSentinel(label="bench_mpmd_sync")

    def fetched(m):
        force_fetch(m)
        return jax.tree.map(np.asarray, m)

    # Warm one chunk through each engine (absorbs one-time transfer
    # programs) before the armed, timed windows.
    _, m = mono(clone(state0), batch)
    force_fetch(m)
    _, m = mpmd(clone(state0), batch)
    force_fetch(m)

    # Monolithic sync loop: block on the metrics after every chunk.
    s = clone(state0)
    hist_mono = []
    with sentinel.armed():
        t0 = time.perf_counter()
        for _ in range(chunks):
            s, m = mono(s, batch)
            hist_mono.append(fetched(m))
        mono_sync_s = (time.perf_counter() - t0) / (chunks * rps)
    state_mono = jax.tree.map(np.asarray, s)

    # MPMD sync loop: the production one-chunk pending lag — dispatch
    # chunk k+1's DAG, THEN drain chunk k's already-pushed metrics.
    s = clone(state0)
    hist_mpmd = []
    pend = None
    dispatch = []
    with sentinel.armed():
        t0 = time.perf_counter()
        for _ in range(chunks):
            td = time.perf_counter()
            s, m = mpmd(s, batch)
            dispatch.append(time.perf_counter() - td)
            if pend is not None:
                hist_mpmd.append(fetched(pend))
            pend = m
        hist_mpmd.append(fetched(pend))
        mpmd_sync_s = (time.perf_counter() - t0) / (chunks * rps)
    state_mpmd = jax.tree.map(np.asarray, s)

    bad = 0
    for a, b in zip(hist_mono, hist_mpmd):
        if jax.tree.structure(a) != jax.tree.structure(b):
            raise RuntimeError("--mpmd sync row: metric tree structure "
                               "diverged from the monolithic oracle")
        bad += sum(not np.array_equal(x, y) for x, y in
                   zip(jax.tree.leaves(a), jax.tree.leaves(b)))
    bad += sum(not np.array_equal(x, y) for x, y in
               zip(jax.tree.leaves(state_mono),
                   jax.tree.leaves(state_mpmd)))
    if bad:
        raise RuntimeError(
            f"--mpmd sync row lost bitwise parity with the monolithic "
            f"oracle: {bad} leaves differ across {chunks} chunks")

    assert_above_flops_floor(mono_sync_s, flops, peak,
                             label="mpmd-row mono sync")
    assert_above_flops_floor(mpmd_sync_s, flops, peak,
                             label="mpmd-row mpmd sync")

    # Host dispatch cost per chunk — a DIAGNOSTIC, not a model input: on
    # an async transport (the tunnel) it is the DAG enqueue cost; on a
    # synchronous local backend the call blocks through the compute and
    # this number degenerates to ~chunk compute.
    host_dispatch_s = float(np.median(dispatch))
    # The schedule model, at BENCH_r05's recorded operating point only:
    # the lag-1 pending schedule removes the per-chunk dispatch+fetch
    # RTT from the critical path (chunk k's fetch drains under chunk
    # k+1's compute), so sync-mode cost collapses to the pipelined
    # chunk cost and the improvement is (compute + rtt) / compute.
    modeled = (TUNNEL_CHUNK_COMPUTE_S + TUNNEL_RTT_S) \
        / TUNNEL_CHUNK_COMPUTE_S
    return {"rounds_per_step": rps,
            "sync_s": mono_sync_s,
            "mpmd_sync_s": mpmd_sync_s,
            "improvement_measured": mono_sync_s / mpmd_sync_s,
            "parity_bitwise": True,
            "chunks_compared": chunks,
            "recompiles": sentinel.count,
            "host_dispatch_s": host_dispatch_s,
            "improvement_modeled_tunnel": modeled,
            "model": {"tunnel_chunk_compute_s": TUNNEL_CHUNK_COMPUTE_S,
                      "tunnel_rtt_s": TUNNEL_RTT_S,
                      "source": "BENCH_r05 rps=100 recorded sync vs "
                                "pipelined split; lag-1 schedule takes "
                                "the rtt off the critical path"}}


def bench_reference_equivalent(ds) -> dict:
    """Measured reference-equivalent baseline; see module docstring."""
    import torch
    import torch.nn as nn

    def make_model():
        # Same architecture as FL_CustomMLP...:12-25, hidden [50, 200] (:40).
        return nn.Sequential(
            nn.Linear(ds.input_dim, 50), nn.ReLU(),
            nn.Linear(50, 200), nn.ReLU(),
            nn.Linear(200, ds.num_classes))

    torch.set_num_threads(max(1, os.cpu_count() or 1))
    n = len(ds.x_train)
    chunk = max(1, n // NUM_CLIENTS)
    shards = []
    for r in range(NUM_CLIENTS):
        s, e = r * chunk, (r + 1) * chunk if r != NUM_CLIENTS - 1 else n
        shards.append((torch.tensor(ds.x_train[s:e]),
                       torch.tensor(ds.y_train[s:e], dtype=torch.long)))

    models = [make_model() for _ in range(NUM_CLIENTS)]
    opts = [torch.optim.Adam(m.parameters(), lr=0.004) for m in models]
    scheds = [torch.optim.lr_scheduler.StepLR(o, step_size=30, gamma=0.5)
              for o in opts]
    crit = nn.CrossEntropyLoss()

    def one_round():
        t_compute = 0.0
        t_serial = 0.0
        gathered = []
        sizes = []
        for m, o, sch, (x, y) in zip(models, opts, scheds, shards):
            t0 = time.perf_counter()
            # train_one_epoch (:63-73): one full-batch fwd/bwd/Adam step.
            o.zero_grad()
            loss = crit(m(x), y)
            loss.backward()
            o.step()
            sch.step()
            # evaluate_local (:75-91): argmax on the local shard.
            with torch.no_grad():
                m(x).argmax(dim=1).numpy()
            t_compute += time.perf_counter() - t0

            t0 = time.perf_counter()
            # get_weights + comm.gather pickling (:93-94,105).
            w = {k: v.detach().numpy().copy()
                 for k, v in m.named_parameters()}
            gathered.append(pickle.loads(pickle.dumps(w)))
            sizes.append(len(x))
            t_serial += time.perf_counter() - t0

        t0 = time.perf_counter()
        # rank-0 weighted average (:108-116).
        total = sum(sizes)
        avg = {k: sum(g[k] * (s / total) for g, s in zip(gathered, sizes))
               for k in gathered[0]}
        # comm.bcast back out + set_weights (:119-120).
        for m in models:
            blob = pickle.loads(pickle.dumps(avg))
            with torch.no_grad():
                for k, p in m.named_parameters():
                    p.copy_(torch.tensor(blob[k]))
        t_serial += time.perf_counter() - t0
        return t_compute, t_serial

    one_round()  # warmup
    reps = 5
    rounds = [one_round() for _ in range(reps)]
    # mpirun runs ranks concurrently: ideal-parallel compute, serial comm.
    parallel = min(NUM_CLIENTS, os.cpu_count() or 1)
    # Min over reps, not mean: transient load on this shared box inflates
    # the baseline and would overstate OUR speedup — take the reference's
    # least-contended (fastest) showing of the REPORTED metric (the
    # parallel-credited sum, not raw tc+ts, which could pick a rep whose
    # reported value is actually slower on a multi-core box).
    tc, ts = min(rounds, key=lambda r: r[0] / parallel + r[1])
    return {"sec_per_round": tc / parallel + ts,
            "compute_s": tc, "serial_s": ts, "assumed_parallelism": parallel}


def emit_result(result: dict, detail_lines, out_path=None) -> str:
    """Emit the benchmark artifact in consumer-safe order.

    Detail lines go to stderr FIRST, then the full JSON blob is written to
    ``out_path`` (when given) and printed LAST on stdout. Harnesses that
    read "the last stdout line" or "everything after the last brace" get a
    complete, parseable document — the earlier ordering (JSON first) let
    interleaved stream flushing truncate the blob and parse to null.
    """
    for line in detail_lines:
        print(line, file=sys.stderr)
    blob = json.dumps(result)
    if out_path:
        with open(out_path, "w") as f:
            f.write(blob + "\n")
    sys.stderr.flush()
    print(blob, flush=True)
    return blob


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_RESULT.json",
                    help="file the full JSON result is written to "
                         "(default: %(default)s)")
    ap.add_argument("--events", default=None, metavar="PATH",
                    help="telemetry JSONL sink for per-stage bench spans "
                         "(inspect with 'fedtpu report PATH')")
    ap.add_argument("--compilation-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache dir; a warm "
                         "cache collapses cold_compile_s to the "
                         "deserialize cost (docs/performance.md)")
    args = ap.parse_args(argv)

    if args.compilation_cache:
        from fedtpu.compilation import configure_persistent_cache
        configure_persistent_cache(args.compilation_cache)

    from fedtpu.telemetry import build_manifest, make_tracer
    tracer = make_tracer(args.events)
    if tracer.enabled:
        tracer.event("manifest", **build_manifest(
            extra={"program": "bench", "headline_rps": HEADLINE_RPS}))

    with tracer.span("dataset"):
        ds = _dataset()
    with tracer.span("bench_fedtpu"):
        ours = bench_fedtpu(ds)
    with tracer.span("mfu_capability"):
        capability = bench_mfu_capability(ours["peak_flops_measured"])
    with tracer.span("mpmd_sync"):
        mpmd_row = bench_mpmd_sync(ds, ours["peak_flops_measured"])
    with tracer.span("baseline"):
        base = bench_reference_equivalent(ds)
    lo, hi = ours["sec_per_round_range"]
    g3 = lambda v: float(f"{v:.3g}")
    result = {
        "metric": "sec_per_round_fedavg8_income_mlp",
        # 3 significant figures — the value sits at sub-millisecond scale
        # where fixed decimals would destroy it. The headline is the MEDIAN
        # of 5 independent timed windows; vs_baseline_range is the full
        # window band, so the single number can never travel without its
        # jitter (review r2).
        "value": g3(ours["sec_per_round"]),
        "unit": "s",
        "vs_baseline": float(
            f"{base['sec_per_round'] / ours['sec_per_round']:.4g}"),
        "vs_baseline_range": [g3(base["sec_per_round"] / hi),
                              g3(base["sec_per_round"] / lo)],
        "mfu": g3(ours["mfu"]),
        # Backend compiles observed INSIDE timed windows (recompile
        # sentinel, fedtpu.analysis.guards): must be 0 — a nonzero count
        # means the quoted numbers include silent retrace cost.
        "recompiles": ours["recompiles"],
        # Startup-cost pair for the headline program: trace+compile from
        # nothing vs a warm ProgramCache deserialize (what a
        # --compilation-cache / 'fedtpu warmup' start pays instead).
        "cold_compile_s": g3(ours["cold_compile_s"])
        if ours["cold_compile_s"] is not None else None,
        "warm_lookup_ms": g3(ours["warm_lookup_ms"])
        if ours["warm_lookup_ms"] is not None else None,
        # The headline mfu above is the income workload's BANDWIDTH roofline
        # (~22% marginal, byte-bound — RESULTS.md); this row is the same
        # engine at an MXU-sized shape, dispatch-cancelled slope timing.
        "mfu_capability": {
            "hidden": capability["hidden"],
            "rows_per_client": capability["rows_per_client"],
            "marginal_s_per_round": g3(capability["marginal_s_per_round"]),
            "flops_per_round": g3(capability["flops_per_round"]),
            "mfu": g3(capability["mfu"]),
        },
        "sweep": {str(rps): {"pipelined_s": g3(row["sec_per_round"]),
                             "sync_s": g3(row["sec_per_round_sync"]),
                             "mfu": g3(row["mfu"])}
                  for rps, row in ours["sweep"].items()},
        # PR 18 --mpmd sync-mode row (bench_mpmd_sync): the early-stop
        # loop shape through the MPMD DAG, bitwise metric-history parity
        # re-proven in-run (the bench crashes otherwise). The measured
        # ratio is this backend's transport; the modeled ratio is the
        # BENCH_r05 tunnel operating point, labeled as a model with its
        # inputs alongside.
        "mpmd_sync": {
            "rounds_per_step": mpmd_row["rounds_per_step"],
            "sync_s": g3(mpmd_row["sync_s"]),
            "mpmd_sync_s": g3(mpmd_row["mpmd_sync_s"]),
            "improvement_measured": g3(mpmd_row["improvement_measured"]),
            "improvement_modeled_tunnel": g3(
                mpmd_row["improvement_modeled_tunnel"]),
            "parity_bitwise": mpmd_row["parity_bitwise"],
            "chunks_compared": mpmd_row["chunks_compared"],
            "recompiles": mpmd_row["recompiles"],
            "host_dispatch_s": g3(mpmd_row["host_dispatch_s"]),
            "model": {
                "tunnel_chunk_compute_s": g3(
                    mpmd_row["model"]["tunnel_chunk_compute_s"]),
                "tunnel_rtt_s": g3(mpmd_row["model"]["tunnel_rtt_s"]),
                "source": mpmd_row["model"]["source"],
            },
        },
        "baseline": {
            "sec_per_round": g3(base["sec_per_round"]),
            "assumed_parallelism": base["assumed_parallelism"],
            # The parallel-credit caveat must ride IN the artifact: the
            # baseline's compute term is divided by min(8, cpu_count).
            # On this 1-core box that credit is 1; on an 8-core host the
            # reference's compute shrinks up to 8x and the quoted speedup
            # drops accordingly (see vs_baseline_if_8cores).
            "vs_baseline_if_8cores": g3(
                (base["compute_s"] / 8 + base["serial_s"])
                / ours["sec_per_round"]),
        },
    }
    # Detail lines accumulate here and hit stderr BEFORE the JSON blob —
    # the complete JSON must be the LAST thing on stdout (emit_result).
    detail = [
        f"[bench] headline (rps={HEADLINE_RPS}, pipelined): "
        f"{ours['sec_per_round']:.3e} s/round "
        f"(window band [{lo:.3e}, {hi:.3e}]; "
        f"synchronous {ours['sec_per_round_sync']:.3e}), "
        f"accuracy {ours['accuracy']:.4f}, devices {ours['devices']}, "
        f"backend {ours['backend']}, measured peak "
        f"{ours['peak_flops_measured'] / 1e12:.1f} TFLOP/s, "
        f"{ours['flops_per_round']:.2e} FLOPs/round, "
        f"MFU {100 * ours['mfu']:.1f}%, "
        f"{ours['recompiles']} in-window recompiles",
        f"[bench] headline compile cost: cold {ours['cold_compile_s']:.3f} s"
        f", warm deserialize {ours['warm_lookup_ms']:.1f} ms"
        if ours["cold_compile_s"] is not None
        and ours["warm_lookup_ms"] is not None else
        "[bench] headline compile cost: unavailable",
        f"[bench] MFU capability (hidden {capability['hidden']}, "
        f"{capability['rows_per_client']} rows/client, slope-timed): "
        f"{capability['marginal_s_per_round']:.3e} s/round, "
        f"{capability['flops_per_round']:.2e} FLOPs/round, "
        f"MFU {100 * capability['mfu']:.1f}% — the income headline above "
        "is byte-bound at its own roofline (RESULTS.md)",
    ]
    for rps, row in ours["sweep"].items():
        detail.append(
            f"[bench] rps={rps:>4}: pipelined "
            f"{row['sec_per_round']:.3e} s/round, sync "
            f"{row['sec_per_round_sync']:.3e} s/round "
            f"(floor {row['floor_sec']:.3e}, "
            f"MFU {100 * row['mfu']:.1f}%, "
            f"{row['rounds_timed']} rounds/window, "
            f"{row['rounds_trained']} trained)")
    detail.append(
        f"[bench] mpmd sync-mode (rps={mpmd_row['rounds_per_step']}, --mpmd "
        f"DAG, one-chunk lag): {mpmd_row['mpmd_sync_s']:.3e} s/round vs "
        f"monolithic sync {mpmd_row['sync_s']:.3e} — measured "
        f"{mpmd_row['improvement_measured']:.2f}x on this transport, "
        f"modeled {mpmd_row['improvement_modeled_tunnel']:.1f}x at the "
        f"BENCH_r05 tunnel operating point; metric history + final state "
        f"bitwise over {mpmd_row['chunks_compared']} chunks, "
        f"{mpmd_row['recompiles']} in-window recompiles")
    detail.append(
        f"[bench] baseline(measured reference-equivalent): {base} — "
        "compute credited /min(8, cpu_count); an 8-core host shrinks "
        "the baseline and the speedup accordingly")
    if args.out:
        detail.append(f"[bench] full JSON result written to {args.out}")
    emit_result(result, detail, out_path=args.out)
    tracer.event("bench_end", headline_s=result["value"],
                 vs_baseline=result["vs_baseline"])
    tracer.close()


if __name__ == "__main__":
    main()
